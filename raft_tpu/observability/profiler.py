"""The ``res.profiler`` resource: cost capture + roofline + XLA tracing.

One :class:`Profiler` per handle (shared through the process-default
handle exactly like ``res.metrics``): it owns the chip's roofline peaks,
keeps the latest :class:`~raft_tpu.observability.costmodel.CostRecord`
per (entry, shape signature), and publishes every capture into the
metrics registry so the exporters and :func:`roofline_report` see them.

Capture sites (asserted statically by ``tools/check_instrumented.py``):

- ``runtime.entry_points._aot_call`` — every AOT-compiled runtime entry
  records its executable's cost on the compile miss (hits reuse the
  stored record; the cost of an executable is a property of the
  executable, not of the dispatch).
- ``benchmark.Fixture.run`` — benchmarks lower/compile the measured
  callable once per (name, signature) for analysis, so BENCH artifacts
  carry FLOPs/bytes/roofline%% alongside seconds.

Tracing bridge: :meth:`Profiler.trace` wraps ``jax.profiler.trace`` (the
xprof trace writer) and re-announces the current nvtx range stack as
``TraceAnnotation``s inside the trace window, so XLA host-timeline events
attribute to the same range stack the span metrics use. (``core.nvtx``
already opens a ``TraceAnnotation`` per range — the bridge covers ranges
pushed BEFORE the trace window opened, which xprof would otherwise drop.)
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, Iterator, Optional

import jax

from raft_tpu.core import nvtx
from raft_tpu.observability import costmodel
from raft_tpu.observability.costmodel import CostRecord
from raft_tpu.observability.metrics import MetricsRegistry, get_registry
from raft_tpu.observability.spans import span
from raft_tpu.utils.arch import ChipSpec, chip_spec


def _signature(args, kwargs=None) -> str:
    """Shape+dtype+sharding signature of a call — the cost-record key
    (mirrors the CompileCache key structure in runtime.entry_points)."""
    parts = []
    for a in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(a, "shape", None)
        if shape is None:
            parts.append(repr(a))
        else:
            parts.append(f"{getattr(a, 'dtype', '?')}{tuple(shape)}"
                         f"@{getattr(a, 'sharding', None)}")
    return ";".join(parts)


class Profiler:
    """Cost-model store + roofline attribution for one handle.

    Thread-safe; capture never raises into the caller (a failed analysis
    just leaves the entry without a record)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 spec: Optional[ChipSpec] = None):
        self._registry = registry
        self.spec = spec if spec is not None else chip_spec()
        self._lock = threading.Lock()
        self._records: Dict[str, CostRecord] = {}       # latest per entry
        self._by_key: Dict[tuple, CostRecord] = {}      # (entry, key)

    @property
    def registry(self) -> MetricsRegistry:
        # late-bound default so a post-construction set_registry() swap
        # (tests, multi-tenant embedding) is honored
        return self._registry if self._registry is not None \
            else get_registry()

    # -- capture ----------------------------------------------------------
    def capture(self, entry: str, compiled, key: str = ""
                ) -> Optional[CostRecord]:
        """Record ``compiled``'s cost/memory analysis under ``entry``.
        Returns the record (None when the backend exposes no analysis)."""
        rec = costmodel.extract_cost(compiled, entry, key=key)
        if rec is None:
            return None
        rec.platform = jax.default_backend()
        with self._lock:
            self._records[entry] = rec
            self._by_key[(entry, key)] = rec
        costmodel.publish(rec, self.registry)
        return rec

    def capture_fn(self, entry: str, fn: Callable, *args,
                   **kwargs) -> Optional[CostRecord]:
        """Lower+compile ``fn(*args)`` FOR ANALYSIS ONLY and capture its
        cost, memoized by (entry, signature) — repeated benchmark runs of
        the same shape pay one analysis compile total. Jitted callables
        reuse their own lowering path; plain callables are wrapped. Any
        failure (non-jittable fn, backend without analysis) returns the
        memoized/None record without raising."""
        key = _signature(args, kwargs)
        with self._lock:
            hit = self._by_key.get((entry, key))
        if hit is not None:
            # refresh the latest-per-entry pointer and the registry view
            with self._lock:
                self._records[entry] = hit
            return hit
        try:
            target = fn if hasattr(fn, "lower") else jax.jit(fn)
            compiled = target.lower(*args, **kwargs).compile()
        except Exception:
            return None
        rec = self.capture(entry, compiled, key=key)
        # prediction side of the drift ledger: the model's roofline-
        # perfect seconds/bytes for this entry, once per (entry, shape
        # signature). measured=False — never drift-gated; the measured
        # half arrives when benchmark.Fixture.run times the same site.
        if rec is not None:
            try:
                from raft_tpu.observability.timeline import record_drift

                est = costmodel.roofline(rec, self.spec)
                record_drift(entry,
                             predicted_seconds=est.roof_seconds,
                             predicted_bytes=rec.bytes_accessed,
                             measured=False)
            except Exception:
                pass
        return rec

    # -- queries ----------------------------------------------------------
    def records(self) -> Dict[str, CostRecord]:
        """Latest record per entry (a copy)."""
        with self._lock:
            return dict(self._records)

    def get(self, entry: str) -> Optional[CostRecord]:
        with self._lock:
            return self._records.get(entry)

    def roofline(self, entry: str, seconds: Optional[float] = None,
                 f32: bool = False):
        """RooflineEstimate for one captured entry (None if uncaptured).
        ``seconds`` defaults to the entry's latest benchmark event."""
        rec = self.get(entry)
        if rec is None:
            return None
        if seconds is None:
            from raft_tpu.observability.exporters import bench_results

            r = bench_results(self.registry).get(entry, {})
            s = r.get("seconds")
            seconds = s if isinstance(s, (int, float)) else None
        return costmodel.roofline(rec, self.spec, seconds=seconds, f32=f32)

    def report(self) -> str:
        """Roofline summary over THIS profiler's records (see
        :func:`raft_tpu.observability.costmodel.roofline_report`)."""
        return costmodel.roofline_report(
            registry=self.registry, spec=self.spec,
            records=list(self.records().values()))

    # -- xprof bridge -----------------------------------------------------
    @contextlib.contextmanager
    def trace(self, log_dir: Optional[str] = None,
              name: str = "raft_tpu.trace") -> Iterator[None]:
        """Scoped xprof trace attributed to the span range stack.

        With ``log_dir``, starts ``jax.profiler.trace`` (viewable in
        xprof/TensorBoard); without, it is a pure annotation bridge. The
        nvtx ranges already active at entry are re-entered as
        ``TraceAnnotation``s inside the window (ranges opened after entry
        carry their own — see core.nvtx), and the window itself is a
        span, so the trace shows up in the metrics registry too."""
        with contextlib.ExitStack() as stack:
            if log_dir is not None:
                try:
                    stack.enter_context(jax.profiler.trace(log_dir))
                except Exception:
                    from raft_tpu.core.logger import log_warn

                    log_warn("profiler.trace: jax.profiler.trace(%r) "
                             "unavailable — continuing with annotations "
                             "only", log_dir)
            for rng in nvtx.range_stack():
                try:
                    stack.enter_context(jax.profiler.TraceAnnotation(rng))
                except Exception:
                    break
            stack.enter_context(span(name))
            yield


# -- process-global default (the METRICS pattern) -------------------------
_global_profiler: Optional[Profiler] = None
_global_lock = threading.Lock()


def get_profiler() -> Profiler:
    """Process-global Profiler, created lazily on first use — what
    ``res.profiler`` resolves to when no handle-scoped one is set."""
    global _global_profiler
    with _global_lock:
        if _global_profiler is None:
            _global_profiler = Profiler()
        return _global_profiler


def set_profiler(profiler: Profiler) -> Optional[Profiler]:
    """Swap the process-global Profiler (tests). Returns the previous."""
    global _global_profiler
    with _global_lock:
        prev, _global_profiler = _global_profiler, profiler
        return prev
