"""Exporters: Prometheus text, JSON lines, summary table, Perfetto trace.

Three views of one :class:`~raft_tpu.observability.metrics.MetricsRegistry`
plus one of the flight recorder:

- :func:`export_prometheus` — text exposition format (the shape
  ``prometheus_client.generate_latest()`` emits), scrapeable as-is.
  Histograms always carry the explicit cumulative ``le="+Inf"`` bucket
  (== ``_count``) required by the exposition format; note
  ``DEFAULT_TIME_BUCKETS`` tops out at 30 s, so anything slower (a cold
  north-star compile can exceed it) lands only in ``+Inf`` — compile
  timings use :data:`~raft_tpu.observability.metrics.
  COMPILE_TIME_BUCKETS` (reaching 300 s) to keep resolution there.
- :func:`export_jsonl` — one JSON object per line: first the buffered
  event stream (span ends, benchmark results), then a snapshot line per
  metric. The substrate future ``BENCH_*.json`` trajectories are cut from.
- :func:`summary_table` — human-readable aligned table for terminals.
- :func:`export_perfetto` — the flight-recorder ring as a Chrome
  trace-event object (open at https://ui.perfetto.dev or
  chrome://tracing): spans as complete slices, faults/retries/
  degradation rungs as instants, lanes (threads / mesh axes / shards)
  as named tracks.
"""

from __future__ import annotations

import io
import json
import math
import os
from typing import Dict, Optional

from raft_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)


def _fmt_value(v: float) -> str:
    """Prometheus value rendering: integers without a trailing .0."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def _escape_label(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double quote and newline (site names can carry paths — a literal
    backslash or an embedded newline would corrupt the scrape)."""
    return v.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: the exposition format escapes backslash and
    newline ONLY (quotes are legal in help text). Without this a
    multi-line help string splits the ``# HELP`` line and the scraper
    rejects the whole page."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None
               ) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{k}="{_escape_label(str(v))}"'
                    for k, v in sorted(merged.items()))
    return "{" + body + "}"


def export_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format v0.0.4."""
    reg = registry if registry is not None else get_registry()
    out = io.StringIO()
    seen_header = set()

    def header(name: str, kind: str) -> None:
        if name in seen_header:
            return
        seen_header.add(name)
        help_text = reg.help_of(name)
        if help_text:
            out.write(f"# HELP {name} {_escape_help(help_text)}\n")
        out.write(f"# TYPE {name} {kind}\n")

    for metric in reg.collect():
        if isinstance(metric, Counter):
            header(metric.name, "counter")
            out.write(f"{metric.name}{_label_str(metric.labels)} "
                      f"{_fmt_value(metric.value)}\n")
        elif isinstance(metric, Gauge):
            header(metric.name, "gauge")
            out.write(f"{metric.name}{_label_str(metric.labels)} "
                      f"{_fmt_value(metric.value)}\n")
        elif isinstance(metric, Histogram):
            header(metric.name, "histogram")
            cumulative = metric.cumulative_counts()
            bounds = [*metric.buckets, math.inf]
            for le, c in zip(bounds, cumulative):
                ls = _label_str(metric.labels, {"le": _fmt_value(le)})
                out.write(f"{metric.name}_bucket{ls} {c}\n")
            out.write(f"{metric.name}_sum{_label_str(metric.labels)} "
                      f"{_fmt_value(metric.sum)}\n")
            out.write(f"{metric.name}_count{_label_str(metric.labels)} "
                      f"{metric.count}\n")
    return out.getvalue()


def export_jsonl(registry: Optional[MetricsRegistry] = None,
                 events: bool = True) -> str:
    """One JSON object per line: buffered events (oldest first), then a
    ``{"type": "metric", ...}`` snapshot line per live metric."""
    reg = registry if registry is not None else get_registry()
    lines = []
    if events:
        for ev in list(reg.events):
            lines.append(json.dumps(ev, sort_keys=True, default=str))
    for metric in reg.collect():
        rec = {"type": "metric", "name": metric.name, "labels": metric.labels}
        if isinstance(metric, Counter):
            rec.update(kind="counter", value=metric.value)
        elif isinstance(metric, Gauge):
            rec.update(kind="gauge", value=metric.value)
        elif isinstance(metric, Histogram):
            rec.update(kind="histogram", sum=metric.sum, count=metric.count,
                       buckets=list(metric.buckets),
                       bucket_counts=metric.bucket_counts())
        lines.append(json.dumps(rec, sort_keys=True, default=str))
    return "\n".join(lines) + ("\n" if lines else "")


def summary_table(registry: Optional[MetricsRegistry] = None) -> str:
    """Aligned human-readable metric table. Histograms render
    count/mean/p50/p99/sum — the percentiles are bucket-interpolated
    estimates (:meth:`~raft_tpu.observability.metrics.Histogram.
    percentile`), so latency histograms are actually readable in a
    ``statusz`` snapshot instead of just a sum/count pair."""
    reg = registry if registry is not None else get_registry()
    rows = []
    for metric in reg.collect():
        label_s = ",".join(f"{k}={v}" for k, v in sorted(metric.labels.items()))
        if isinstance(metric, Histogram):
            cnt = metric.count
            mean = metric.sum / cnt if cnt else 0.0
            p50, p99 = metric.percentile(50), metric.percentile(99)
            pct = (f" p50={p50:.6g} p99={p99:.6g}"
                   if p50 is not None else " p50=- p99=-")
            rows.append((metric.name, label_s,
                         f"count={cnt} mean={mean:.6g}{pct} "
                         f"sum={metric.sum:.6g}"))
        else:
            rows.append((metric.name, label_s, _fmt_value(metric.value)))
    if not rows:
        return "(no metrics recorded)\n"
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    out = io.StringIO()
    out.write(f"{'metric'.ljust(w0)}  {'labels'.ljust(w1)}  value\n")
    out.write(f"{'-' * w0}  {'-' * w1}  {'-' * 5}\n")
    for name, label_s, val in rows:
        out.write(f"{name.ljust(w0)}  {label_s.ljust(w1)}  {val}\n")
    return out.getvalue()


#: event fields consumed by the trace-event envelope itself; everything
#: else a flight event carries rides in Perfetto's ``args`` pane.
#: ``flow_id`` becomes the trace event's ``id`` (flow binding key).
_PERFETTO_ENVELOPE = ("kind", "name", "ph", "ts", "dur", "lane",
                      "flow_id")


def export_perfetto(recorder=None) -> Dict:
    """Flight-recorder ring → Chrome trace-event JSON object.

    Every flight event becomes one trace event with the REQUIRED keys
    ``ph``/``ts``/``pid``/``tid``/``name`` (+ ``dur`` for complete
    slices); ``kind`` becomes the category (``cat``), the remaining
    fields the ``args`` dict. Timestamps are the recorder's monotonic
    seconds converted to microseconds (Perfetto's unit). Lanes (thread
    names, ``comms:<axis>``, shards) map to stable ``tid``s with a
    ``thread_name`` metadata event each, so Perfetto renders one named
    track per lane. Serializable as-is with ``json.dump``.
    """
    from raft_tpu.observability.flight import get_flight_recorder

    rec = recorder if recorder is not None else get_flight_recorder()
    pid = os.getpid()
    lanes: Dict[str, int] = {}
    out = []
    for ev in rec.events():
        lane = str(ev.get("lane") or "main")
        tid = lanes.get(lane)
        if tid is None:
            tid = lanes[lane] = len(lanes) + 1
        ph = ev.get("ph", "i")
        te: Dict = {
            "name": str(ev.get("name", ev.get("kind", "?"))),
            "cat": str(ev.get("kind", "event")),
            "ph": ph,
            "ts": float(ev.get("ts", 0.0)) * 1e6,
            "pid": pid,
            "tid": tid,
        }
        if ph == "X":
            te["dur"] = max(float(ev.get("dur", 0.0)), 0.0) * 1e6
        elif ph == "i":
            te["s"] = "t"          # instant scoped to its thread track
        elif ph in ("s", "t", "f"):
            # flow events bind on (cat, name, id): the per-request
            # journey (enqueue → batch → dispatch → response) renders
            # as one connected arrow chain across lanes
            te["id"] = str(int(ev.get("flow_id", 0)))
            if ph == "f":
                te["bp"] = "e"     # bind the terminus to the enclosing
                #                    slice, Chrome's recommended mode
        args = {k: v for k, v in ev.items()
                if k not in _PERFETTO_ENVELOPE and v is not None}
        if args:
            te["args"] = args
        out.append(te)
    for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        out.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                    "pid": pid, "tid": tid, "args": {"name": lane}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def bench_results(registry: Optional[MetricsRegistry] = None) -> Dict[str, Dict]:
    """{bench name: latest benchmark-event payload} — the queryable form
    of what :meth:`raft_tpu.benchmark.Fixture.run` emitted; BENCH_*.json
    writers consume this instead of re-implementing collection."""
    reg = registry if registry is not None else get_registry()
    out: Dict[str, Dict] = {}
    for ev in reg.events:
        if ev.get("type") == "benchmark":
            out[ev["bench"]] = {k: v for k, v in ev.items()
                                if k not in ("type", "bench")}
    return out
