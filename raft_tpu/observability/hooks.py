"""Reporting hooks the rest of raft_tpu calls into.

One tiny function per instrumented subsystem, so call sites stay a
single line and the naming scheme lives in exactly one place:

- comms      → :func:`record_collective`  (``raft_tpu.comms.comms``)
- compile    → :func:`record_cache`       (``core.resources.CompileCache``)
- memory     → :func:`record_alloc` / :func:`record_free`
  (``core.memory.MemoryTracker``)
- benchmarks → :func:`record_benchmark`   (``benchmark.Fixture.run``)

Every hook is a no-op after one ``enabled`` check when tracing is
disabled, and none of them may raise into the hot path.
"""

from __future__ import annotations

from typing import Dict, Optional

from raft_tpu.observability.metrics import get_registry
from raft_tpu.observability.timeline import (emit_benchmark,
                                             emit_collective,
                                             emit_compile)

COMMS_CALLS = "raft_tpu_comms_calls_total"
COMMS_BYTES = "raft_tpu_comms_bytes_total"
CACHE_HITS = "raft_tpu_compile_cache_hits_total"
CACHE_MISSES = "raft_tpu_compile_cache_misses_total"
MEM_ALLOC_CALLS = "raft_tpu_memory_alloc_total"
MEM_ALLOC_BYTES = "raft_tpu_memory_alloc_bytes_total"
MEM_FREE_CALLS = "raft_tpu_memory_free_total"
MEM_CURRENT = "raft_tpu_memory_current_bytes"
MEM_PEAK = "raft_tpu_memory_peak_bytes"
BENCH_SECONDS = "raft_tpu_benchmark_seconds"
BENCH_RUNS = "raft_tpu_benchmark_runs_total"


def record_collective(collective: str, x, axis_name: str = "") -> None:
    """Count one collective invocation and its payload bytes.

    Called from inside ``shard_map``-traced code, so it fires at TRACE
    time: counts are per *traced program build*, not per device
    execution (a jitted program re-running from cache does not re-count).
    That is the honest countable event on an XLA runtime — the collective
    is compiled in once. Payload bytes come from the tracer's aval, which
    carries the true per-shard shape/dtype.
    """
    reg = get_registry()
    if not reg.enabled:
        return
    labels = {"collective": collective, "axis": str(axis_name)}
    reg.counter(COMMS_CALLS, labels,
                help="Collective invocations (counted at trace time)").inc()
    n = getattr(x, "nbytes", None)
    if isinstance(n, int):
        reg.counter(COMMS_BYTES, labels,
                    help="Per-shard payload bytes entering collectives"
                    ).inc(n)
    emit_collective(collective, n if isinstance(n, int) else 0,
                    str(axis_name))


def record_cache(hit: bool) -> None:
    """CompileCache hit/miss accounting."""
    reg = get_registry()
    if not reg.enabled:
        return
    if hit:
        reg.counter(CACHE_HITS, help="CompileCache lookups served from "
                                     "an already-compiled executable").inc()
    else:
        reg.counter(CACHE_MISSES, help="CompileCache lookups that paid a "
                                       "compilation").inc()
    emit_compile("compile_cache", hit=hit)


def record_alloc(nbytes: int, current_bytes: int, peak_bytes: int) -> None:
    """MemoryTracker.allocate bridge: counters + live/peak gauges."""
    reg = get_registry()
    if not reg.enabled:
        return
    reg.counter(MEM_ALLOC_CALLS, help="Logical allocations through "
                                      "MemoryTracker").inc()
    reg.counter(MEM_ALLOC_BYTES, help="Logical bytes allocated through "
                                      "MemoryTracker").inc(max(0, nbytes))
    reg.gauge(MEM_CURRENT, help="Live logical bytes (MemoryTracker)"
              ).set(current_bytes)
    reg.gauge(MEM_PEAK, help="Peak logical bytes (MemoryTracker)"
              ).set(peak_bytes)


def record_free(nbytes: int, current_bytes: int) -> None:
    """MemoryTracker.deallocate bridge."""
    reg = get_registry()
    if not reg.enabled:
        return
    reg.counter(MEM_FREE_CALLS, help="Logical deallocations through "
                                     "MemoryTracker").inc()
    reg.gauge(MEM_CURRENT, help="Live logical bytes (MemoryTracker)"
              ).set(current_bytes)


def record_benchmark(name: str, result: Dict[str, float],
                     nbytes: Optional[float] = None) -> None:
    """Benchmark result → registry: ``Fixture.run`` calls this with its
    RTT-corrected ``seconds`` (device-execute time, unlike the dispatch
    time spans record), so every BENCH artifact flows from one code path.
    """
    reg = get_registry()
    if not reg.enabled:
        return
    labels = {"bench": name}
    reg.histogram(BENCH_SECONDS, labels,
                  help="RTT-corrected execute seconds from benchmark."
                       "Fixture.run").observe(result.get("seconds", 0.0))
    reg.counter(BENCH_RUNS, labels, help="Fixture.run invocations").inc()
    event = {"type": "benchmark", "bench": name}
    event.update({k: v for k, v in result.items()})
    if nbytes is not None:
        event["nbytes"] = nbytes
    reg.emit(event)
    emit_benchmark(name, float(result.get("seconds", 0.0)))
