"""Black-box recorder: a crash-durable mmap ring of forensic records.

Every live surface PR 16 added (statusz, burn alerts, explain records)
dies with the process — and the deaths that matter most (SIGKILL, OOM
kills, native segfaults, silent hangs) are exactly the ones that never
reach a Python ``except`` handler, so :func:`flight.post_mortem` never
fires. This module is the evidence that outlives the process: a
fixed-size memory-mapped ring FILE that mirrors the flight-recorder
timeline, carries periodic compact metrics snapshots and watchdog stall
dumps, and is readable after any death because the OS owns the dirty
pages the moment the ``memcpy`` lands — SIGKILL cannot un-write them.

Design (the WAL framing idiom of :mod:`raft_tpu.mutable.wal`, turned
into a wraparound ring):

- **File layout**: a 64-byte run header (magic, version, ring
  geometry, pid, wall/monotonic start — the clock bridge postmortem
  needs to turn ``perf_counter`` stamps back into wall time) followed
  by a fixed ``ring_bytes`` region of CRC-framed records.
- **Record frame** (little-endian, exactly the WAL shape)::

      magic   4B  b"RBX1"
      version u16 schema version (1)
      rtype   u8  1=event 2=snapshot 3=dump 4=epilogue
      flags   u8  reserved (0)
      seq     u64 monotone record sequence (1-based)
      plen    u32 payload length
      payload plen bytes (compact JSON)
      crc32   u32 over magic..payload

- **Appends are bump-pointer memcpys into the mmap** — no syscalls, no
  fsync on the hot path (page-cache durability survives process death;
  only power loss needs more, and a black box is process-forensics,
  not storage). One writer at a time: the flight-recorder mirror path
  is already serialized per event, and the tiny internal lock only
  orders the rare direct writers (snapshots, the epilogue) against it.
- **Wraparound**: a record that does not fit in the tail of the ring
  zero-fills the remainder and restarts at offset 0, overwriting the
  oldest records. Recovery does a full-ring scan for CRC-valid frames
  and orders them by ``seq`` — the torn frontier (a half-overwritten
  frame) simply fails its CRC and is skipped, exactly like a WAL torn
  tail.
- **Clean vs violent death**: :meth:`BlackBox.close` emits the
  ``epilogue`` flight event and appends the epilogue record as the
  maximum-``seq`` frame. A blackbox whose newest record is NOT an
  epilogue was a violent death — :func:`reconstruct` says so.
- **Zero overhead when disabled**: no blackbox installed means the
  flight mirror is one module-attribute read + ``None`` test per
  event; nothing is allocated, no file exists, no syscall happens.

Enable with ``RAFT_TPU_BLACKBOX_PATH`` (+ ``RAFT_TPU_BLACKBOX_BYTES``,
default 1 MiB) or ``ServingEngine(blackbox_path=...)``; read a dead
process's file with ``python tools/postmortem.py <path>`` or the
restart-surfaced debugz ``/crashz`` route.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
import zlib
from typing import Dict, List, NamedTuple, Optional, Tuple

from raft_tpu.core import env

BB_FILE_MAGIC = b"RBB1"
BB_MAGIC = b"RBX1"
BB_VERSION = 1

#: record types
REC_EVENT, REC_SNAPSHOT, REC_DUMP, REC_EPILOGUE = 1, 2, 3, 4
_REC_NAMES = {REC_EVENT: "event", REC_SNAPSHOT: "snapshot",
              REC_DUMP: "dump", REC_EPILOGUE: "epilogue"}

# file header: magic, version, flags, ring_off, ring_bytes, pid,
# reserved, wall_start, mono_start — padded to HEADER_SIZE
_FILE_HEADER = struct.Struct("<4sHHIQIIdd")
HEADER_SIZE = 64

# record frame header (the WAL _HEADER shape with rtype in the op slot)
_FRAME = struct.Struct("<4sHBBQI")
_CRC = struct.Struct("<I")

BLACKBOX_PATH_ENV = "RAFT_TPU_BLACKBOX_PATH"
BLACKBOX_BYTES_ENV = "RAFT_TPU_BLACKBOX_BYTES"
DEFAULT_RING_BYTES = 1 << 20
_MIN_RING_BYTES = 1 << 14

#: restart-detected violent deaths (bumped by ServingEngine at boot
#: when the prior run's blackbox has no epilogue)
UNCLEAN_SHUTDOWNS = "raft_tpu_unclean_shutdowns_total"

_VERDICTS = ("clean", "crash", "hang")


def ring_bytes_default() -> int:
    n = env.get(BLACKBOX_BYTES_ENV, DEFAULT_RING_BYTES)
    return max(_MIN_RING_BYTES, int(n))


class BlackBox:
    """Writer over one crash-durable ring file.

    ``append()`` frames + CRCs the payload and memcpys it into the
    mmap under a tiny lock — no syscall, no allocation beyond the
    frame bytes. The writer tracks its own overhead
    (``append_seconds``) so benchmarks can stamp an honest overhead
    fraction into the artifact.
    """

    def __init__(self, path: str, nbytes: Optional[int] = None,
                 snapshot_interval_s: float = 1.0):
        self.path = path
        ring = int(nbytes) if nbytes else ring_bytes_default()
        self.ring_bytes = max(_MIN_RING_BYTES, ring)
        self.snapshot_interval_s = max(0.0, float(snapshot_interval_s))
        self._lock = threading.Lock()
        self._off = 0              # write offset within the ring region
        self._seq = 0
        self._closed = False
        self._last_snapshot = 0.0  # monotonic; 0 = never
        # stats (mutated under _lock)
        self.records = 0
        self.bytes_written = 0
        self.append_seconds = 0.0
        self.dropped_oversize = 0
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._file = open(path, "w+b")
        self._file.truncate(HEADER_SIZE + self.ring_bytes)
        self._mm = mmap.mmap(self._file.fileno(),
                             HEADER_SIZE + self.ring_bytes)
        head = _FILE_HEADER.pack(BB_FILE_MAGIC, BB_VERSION, 0,
                                 HEADER_SIZE, self.ring_bytes,
                                 os.getpid(), 0, time.time(),
                                 time.perf_counter())
        self._mm[0:len(head)] = head

    # -- the hot path -----------------------------------------------------
    def append(self, rtype: int, payload: bytes) -> bool:
        """Frame + CRC ``payload`` and memcpy it into the ring. Returns
        False (never raises) when closed or the record exceeds the
        whole ring."""
        t0 = time.perf_counter()
        frame_len = _FRAME.size + len(payload) + _CRC.size
        with self._lock:
            if self._closed:
                return False
            if frame_len > self.ring_bytes:
                self.dropped_oversize += 1
                return False
            self._seq += 1
            head = _FRAME.pack(BB_MAGIC, BB_VERSION, rtype, 0,
                               self._seq, len(payload))
            frame = head + payload + _CRC.pack(
                zlib.crc32(head + payload) & 0xFFFFFFFF)
            if self._off + frame_len > self.ring_bytes:
                # zero the tail so the old frame straddling the wrap
                # point cannot half-parse, then restart at the front
                tail = self.ring_bytes - self._off
                if tail:
                    self._mm[HEADER_SIZE + self._off:
                             HEADER_SIZE + self.ring_bytes] = b"\0" * tail
                self._off = 0
            start = HEADER_SIZE + self._off
            self._mm[start:start + frame_len] = frame
            self._off += frame_len
            self.records += 1
            self.bytes_written += frame_len
            self.append_seconds += time.perf_counter() - t0
        return True

    def append_event(self, event: Dict) -> bool:
        """Mirror one flight event (called by ``FlightRecorder.record``
        for every event when this blackbox is installed). Never raises
        into the emit path."""
        try:
            payload = json.dumps(event, separators=(",", ":"),
                                 default=str).encode()
        except Exception:
            return False
        return self.append(REC_EVENT, payload)

    # -- periodic snapshots ------------------------------------------------
    def snapshot(self, inflight: Optional[List[Dict]] = None,
                 extra: Optional[Dict] = None) -> Optional[Dict]:
        """Append one compact metrics snapshot (counters/gauges by
        name+labels, histogram count/sum/p50/p99, flight ring seq +
        dropped). Never raises; returns the snapshot dict or None."""
        try:
            snap: Dict = {"ts": time.perf_counter(),
                          "wall": time.time(),
                          "metrics": _compact_metrics()}
            try:
                from raft_tpu.observability.flight import (
                    get_flight_recorder, sync_dropped_metric)

                rec = get_flight_recorder()
                snap["flight"] = {"seq": rec.seq,
                                  "dropped": sync_dropped_metric()}
            except Exception:
                pass
            if inflight is not None:
                snap["inflight"] = inflight
            if extra:
                snap.update(extra)
            payload = json.dumps(snap, separators=(",", ":"),
                                 default=str).encode()
        except Exception:
            return None
        self.append(REC_SNAPSHOT, payload)
        self._last_snapshot = time.monotonic()
        return snap

    def maybe_snapshot(self, inflight: Optional[List[Dict]] = None
                       ) -> Optional[Dict]:
        """Rate-limited :meth:`snapshot` (the watchdog calls this every
        tick; most calls are one clock read)."""
        now = time.monotonic()
        if now - self._last_snapshot < self.snapshot_interval_s:
            return None
        return self.snapshot(inflight=inflight)

    def dump(self, payload: Dict) -> bool:
        """Append one watchdog stall dump (thread stacks, in-flight
        table, blocked-lock sites). Never raises."""
        try:
            data = json.dumps(payload, separators=(",", ":"),
                              default=str).encode()
        except Exception:
            return False
        return self.append(REC_DUMP, data)

    # -- lifecycle ---------------------------------------------------------
    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> Dict:
        with self._lock:
            return {"path": self.path,
                    "ring_bytes": self.ring_bytes,
                    "records": self.records,
                    "bytes_written": self.bytes_written,
                    "append_seconds": self.append_seconds,
                    "dropped_oversize": self.dropped_oversize,
                    "seq": self._seq}

    def close(self, reason: str = "clean") -> None:
        """Emit the ``epilogue`` flight event, append the epilogue
        record as the final (max-seq) frame, flush and unmap. A process
        that dies before this leaves an epilogue-less file — which is
        the whole point."""
        if self._closed:
            return
        try:
            from raft_tpu.observability.timeline import emit_epilogue

            emit_epilogue(reason, records=self.records,
                          bytes_written=self.bytes_written)
        except Exception:
            pass
        try:
            payload = json.dumps(
                {"reason": reason, "ts": time.perf_counter(),
                 "wall": time.time(), "records": self.records,
                 "bytes_written": self.bytes_written},
                separators=(",", ":")).encode()
            self.append(REC_EPILOGUE, payload)
        except Exception:
            pass
        with self._lock:
            self._closed = True
        # I/O outside the append lock: flush is advisory (the page
        # cache already owns the bytes); never let it mask shutdown
        try:
            self._mm.flush()
        except Exception:
            pass
        try:
            self._mm.close()
            self._file.close()
        except Exception:
            pass


def _compact_metrics() -> Dict:
    """The registry as one flat JSON-friendly dict: counters/gauges by
    ``name{labels}``, histograms as count/sum/p50/p99."""
    from raft_tpu.observability.metrics import Histogram, get_registry

    out: Dict = {}
    for m in get_registry().collect():
        label_s = ",".join(f"{k}={v}"
                           for k, v in sorted(m.labels.items()))
        key = m.name + (f"{{{label_s}}}" if label_s else "")
        if isinstance(m, Histogram):
            out[key] = {"count": m.count, "sum": round(m.sum, 9),
                        "p50": m.percentile(50), "p99": m.percentile(99)}
        else:
            out[key] = m.value
    return out


# ------------------------------------------------------- process global
_active: Optional[BlackBox] = None
_active_lock = threading.Lock()


def active() -> Optional[BlackBox]:
    """The installed process blackbox, or None (the disabled state)."""
    return _active


def install(bb: Optional[BlackBox]) -> Optional[BlackBox]:
    """Install ``bb`` as the process blackbox AND the flight-recorder
    mirror (None uninstalls). Returns the previous one."""
    global _active
    from raft_tpu.observability import flight

    with _active_lock:
        prev, _active = _active, bb
        flight._mirror = bb
        return prev


class BootResult(NamedTuple):
    """What :func:`boot` found and did."""

    recorder: Optional[BlackBox]   # the installed blackbox (None = off)
    prior: Optional[Dict]          # prior run's reconstruction, if any
    created: bool                  # True when boot opened the file


def boot(path: Optional[str] = None,
         nbytes: Optional[int] = None) -> BootResult:
    """Open-and-install the env/arg-configured blackbox, first
    reconstructing (and preserving as ``<path>.prev``) a prior run's
    file when that run died without an epilogue. No-op returning the
    already-installed recorder when one exists; no-op entirely when
    neither ``path`` nor ``RAFT_TPU_BLACKBOX_PATH`` is set (the
    defaults-off contract). Never raises."""
    if _active is not None:
        return BootResult(_active, None, False)
    if path is None:
        path = env.get(BLACKBOX_PATH_ENV)
    if not path:
        return BootResult(None, None, False)
    prior = None
    try:
        if os.path.exists(path):
            prior = reconstruct(path)
            if prior is not None and prior.get("verdict") != "clean":
                prev_path = path + ".prev"
                try:
                    os.replace(path, prev_path)
                    prior["preserved_path"] = prev_path
                except OSError:
                    pass
        bb = BlackBox(path, nbytes=nbytes)
    except Exception as e:
        from raft_tpu.core.logger import log_warn

        log_warn("blackbox: could not open %s: %s — forensics off",
                 path, e)
        return BootResult(None, prior, False)
    install(bb)
    return BootResult(bb, prior, True)


def shutdown(reason: str = "clean") -> None:
    """Close the installed blackbox with an epilogue and uninstall the
    mirror (the clean-shutdown half of the verdict contract)."""
    bb = _active
    if bb is None:
        return
    install(None)
    bb.close(reason=reason)


# --------------------------------------------------------------- reader
def _parse_file_header(data: bytes) -> Dict:
    if len(data) < HEADER_SIZE:
        raise ValueError("blackbox: file shorter than the run header")
    (magic, version, _flags, ring_off, ring_bytes, pid, _res,
     wall_start, mono_start) = _FILE_HEADER.unpack_from(data, 0)
    if magic != BB_FILE_MAGIC:
        raise ValueError(f"blackbox: bad file magic {magic!r}")
    if version > BB_VERSION:
        raise ValueError(f"blackbox: future schema version {version}")
    return {"version": version, "ring_off": ring_off,
            "ring_bytes": ring_bytes, "pid": pid,
            "wall_start": wall_start, "mono_start": mono_start}


def scan_ring(data: bytes) -> Tuple[List[Tuple[int, int, bytes]], int]:
    """Full-ring scan for CRC-valid frames → ([(seq, rtype, payload)]
    in seq order, torn-candidate count). The write frontier's
    half-overwritten frame, zero-fill pads and stale wrap remnants all
    fail magic/CRC and are skipped — the WAL torn-tail contract,
    applied to a ring."""
    recs: Dict[int, Tuple[int, int, bytes]] = {}
    torn = 0
    off, end = 0, len(data)
    min_frame = _FRAME.size + _CRC.size
    while off + min_frame <= end:
        if data[off:off + 4] != BB_MAGIC:
            off += 1
            continue
        _magic, version, rtype, _flags, seq, plen = _FRAME.unpack_from(
            data, off)
        body_end = off + _FRAME.size + plen
        if version > BB_VERSION or body_end + _CRC.size > end:
            torn += 1
            off += 1
            continue
        (crc,) = _CRC.unpack_from(data, body_end)
        if crc != (zlib.crc32(data[off:body_end]) & 0xFFFFFFFF):
            torn += 1
            off += 1
            continue
        recs[seq] = (seq, rtype,
                     bytes(data[off + _FRAME.size:body_end]))
        off = body_end + _CRC.size
    return [recs[s] for s in sorted(recs)], torn


def read_blackbox(path: str) -> Dict:
    """Parse one blackbox file: run header + every recoverable record
    (seq order, JSON-decoded; undecodable payloads counted, not
    raised). Raises only on a missing/um-parseable FILE header — a
    torn ring never raises."""
    with open(path, "rb") as f:
        data = f.read()
    header = _parse_file_header(data)
    ring = data[header["ring_off"]:
                header["ring_off"] + header["ring_bytes"]]
    raw, torn = scan_ring(ring)
    records, undecodable = [], 0
    for seq, rtype, payload in raw:
        try:
            body = json.loads(payload.decode())
        except Exception:
            undecodable += 1
            continue
        records.append({"seq": seq, "rtype": rtype,
                        "type": _REC_NAMES.get(rtype, f"rtype{rtype}"),
                        "body": body})
    return {"path": path, "header": header, "records": records,
            "torn_records": torn, "undecodable_records": undecodable}


def reconstruct(path: str, tail_events: int = 0) -> Optional[Dict]:
    """The postmortem view of one blackbox file, or None when the file
    is missing/unreadable (a restart probe, not an error path).

    The verdict:

    - ``clean`` — the newest record is an epilogue (the process called
      :meth:`BlackBox.close`);
    - ``hang``  — no epilogue, and the watchdog got a stall dump (or
      ``stall`` flight event) into the ring before death;
    - ``crash`` — no epilogue, no stall evidence: the process died
      violently with the batcher still healthy (SIGKILL, OOM, native
      crash).

    Also reconstructs: the flight-event tail (all recovered events, or
    the newest ``tail_events``), the FINAL metrics snapshot, the alert
    transitions still firing at death, and the in-flight request table
    from the newest stall dump / snapshot that carried one."""
    try:
        parsed = read_blackbox(path)
    except (OSError, ValueError):
        return None
    records = parsed["records"]
    events = [r["body"] for r in records if r["rtype"] == REC_EVENT]
    snapshots = [r["body"] for r in records
                 if r["rtype"] == REC_SNAPSHOT]
    dumps = [r["body"] for r in records if r["rtype"] == REC_DUMP]
    epilogue = None
    if records and records[-1]["rtype"] == REC_EPILOGUE:
        epilogue = records[-1]["body"]
    stalls = [e for e in events if e.get("kind") == "stall"]
    if epilogue is not None:
        verdict = "clean"
    elif dumps or stalls:
        verdict = "hang"
    else:
        verdict = "crash"
    # alert transitions: the last state per (slo, severity) wins
    alert_state: Dict[Tuple[str, str], Dict] = {}
    for e in events:
        if e.get("kind") != "alert":
            continue
        key = (str(e.get("name")), str(e.get("severity")))
        alert_state[key] = e
    firing = [e for e in alert_state.values()
              if e.get("state") == "firing"]
    # in-flight at death: newest dump wins, else newest snapshot
    inflight = None
    for source in (dumps, snapshots):
        for body in reversed(source):
            if body.get("inflight") is not None:
                inflight = body["inflight"]
                break
        if inflight is not None:
            break
    if tail_events and len(events) > tail_events:
        events = events[-tail_events:]
    return {
        "path": path,
        "verdict": verdict,
        "pid": parsed["header"]["pid"],
        "wall_start": parsed["header"]["wall_start"],
        "mono_start": parsed["header"]["mono_start"],
        "ring_bytes": parsed["header"]["ring_bytes"],
        "records": len(records),
        "torn_records": parsed["torn_records"],
        "undecodable_records": parsed["undecodable_records"],
        "events": events,
        "snapshots": len(snapshots),
        "final_snapshot": snapshots[-1] if snapshots else None,
        "stall_dumps": dumps,
        "stall_events": stalls,
        "firing_alerts": firing,
        "inflight": inflight,
        "epilogue": epilogue,
    }
