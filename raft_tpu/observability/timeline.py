"""Typed timeline emitters + the model-vs-measured drift ledger.

The one place the flight-recorder event vocabulary is spelled out: every
subsystem that participates in the timeline calls ONE helper here (the
way metric naming lives in :mod:`raft_tpu.observability.hooks`), so
event shapes stay consistent and the disabled fast path stays a single
boolean test — no helper computes an argument before checking
``recorder.enabled``.

Emitters → :data:`raft_tpu.observability.flight.KNOWN_EVENT_KINDS`:

- :func:`emit_span` (``span``) — from ``spans._record``: complete
  events carrying begin+duration, bytes in/out and the nvtx stack.
- :func:`emit_collective` (``collective``) — from
  ``hooks.record_collective``: per-shard payload bytes and axis, fired
  at TRACE time (the honest countable event on an XLA runtime).
- :func:`emit_compile` / :func:`emit_dispatch` (``compile`` /
  ``dispatch``) — from ``runtime.entry_points._aot_call`` and the
  CompileCache bridge.
- :func:`emit_fault` / :func:`emit_retry` / :func:`emit_degradation`
  (``fault`` / ``retry`` / ``degradation``) — from
  :mod:`raft_tpu.resilience`; ladder walks become visible in Perfetto,
  not just counters.
- :func:`emit_deadline` (``deadline``) — scope armed / scope fired.
- :func:`emit_error` (``error``) — every ``classify_xla_error``
  classification.
- :func:`emit_benchmark` (``benchmark``), :func:`emit_marker`
  (``marker``).

Drift ledger
------------
:class:`DriftLedger` is the durable record of *cost-model prediction
vs. measurement* per site: every ``benchmark.Fixture.run`` (and the
prediction side of ``Profiler.capture_fn``) appends one entry with the
model's seconds/bytes, the measured wall time, and a ``measured`` flag
(True only on real TPU hardware — CPU-suite entries are model-shape
evidence, never calibration evidence). ``tools/bench_report.py
--check`` gates the latest MEASURED entry per site against
:data:`DRIFT_BAND` — so the first measured TPU round automatically
*recalibrates* the modeled rankings (``choose_merge_strategy``, the
``measured: false`` tune tables) instead of just replacing them.
Persistence is opt-in: in-memory always; written to
``RAFT_TPU_DRIFT_LEDGER`` (path) when set, or via :meth:`DriftLedger.
save` (the benchmarks write ``DRIFT_LEDGER.json`` at the repo root).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from raft_tpu.observability.flight import get_flight_recorder

#: flag a site when predicted and measured disagree by more than this
#: factor (either direction). Mirrored in tools/bench_report.py (which
#: stays raft_tpu-import-free); tests/test_flight.py pins them equal.
DRIFT_BAND = 3.0

DRIFT_SCHEMA = 1
DRIFT_RECORDS = "raft_tpu_drift_records_total"
DRIFT_RATIO = "raft_tpu_drift_seconds_ratio"
#: ledger loads that degraded to empty, by reason (unreadable /
#: invalid) — the PR-5 tune-loader convention: counted always, WARNed
#: once per (path, reason) per process. A silently-empty evidence
#: trail was the old behavior this counter replaces.
DRIFT_DEGRADED = "raft_tpu_drift_ledger_degraded_total"

_degraded_warned: set = set()


def _ledger_degraded(path: str, reason: str, detail: str = "") -> None:
    try:
        from raft_tpu.observability.metrics import get_registry

        get_registry().counter(
            DRIFT_DEGRADED, {"reason": reason},
            help="Drift-ledger loads degraded to empty, by reason"
        ).inc()
    except Exception:
        pass
    key = (path, reason)
    if key not in _degraded_warned:
        _degraded_warned.add(key)
        from raft_tpu.core.logger import log_warn

        log_warn("drift ledger %s degraded to empty (%s)%s — this WARN "
                 "fires once per process; the drift_ledger_degraded "
                 "counter keeps counting", path, reason,
                 f": {detail}" if detail else "")


def _reset_degraded_warnings() -> None:
    """Test hook: re-arm the once-per-process WARN."""
    _degraded_warned.clear()


def _now() -> float:
    return time.perf_counter()


# ------------------------------------------------------------- emitters
def emit_span(name: str, parent: str, seconds: float, bytes_in: int,
              bytes_out: int, error: bool,
              stack: Optional[List[str]] = None) -> None:
    """One completed instrumented span (ph=X, begin = now − seconds)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("span", name, ts=_now() - seconds, dur=seconds, ph="X",
               stack=stack, range=parent, bytes_in=bytes_in,
               bytes_out=bytes_out, error=error)


def emit_collective(collective: str, nbytes: int, axis: str) -> None:
    """One comms collective (trace-time; lane = the mesh axis)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("collective", collective, lane=f"comms:{axis or '?'}",
               bytes=nbytes, axis=axis)


def emit_compile(entry: str, seconds: float = 0.0,
                 hit: Optional[bool] = None) -> None:
    """A CompileCache hit/miss or a timed AOT compile (ph=X when a
    duration is known)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    if seconds:
        rec.record("compile", entry, ts=_now() - seconds, dur=seconds,
                   ph="X", hit=bool(hit) if hit is not None else False)
    else:
        rec.record("compile", entry,
                   hit=bool(hit) if hit is not None else None)


def emit_dispatch(entry: str) -> None:
    """One AOT executable dispatch."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("dispatch", entry)


def emit_fault(site: str, kind: str) -> None:
    """One injected fault firing at ``site``."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("fault", site, fault_kind=kind)


def emit_retry(site: str, attempt: int, error: str) -> None:
    """One bounded-retry attempt."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("retry", site, attempt=attempt, error=error[:200])


def emit_degradation(site: str, action: str) -> None:
    """One graceful-degradation ladder rung (policy.record_degradation)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("degradation", site, action=action)


def emit_deadline(label: str, seconds: Optional[float], fired: bool,
                  stack: Optional[List[str]] = None) -> None:
    """A deadline scope armed (``fired=False``) or converting a hang
    into DeadlineExceededError (``fired=True``)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("deadline", label, stack=stack, budget_seconds=seconds,
               fired=fired)


def emit_error(error_type: str, message: str,
               context: str = "") -> None:
    """One classified device error."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("error", error_type, message=message[:300],
               context=context)


def emit_benchmark(name: str, seconds: float) -> None:
    """One Fixture.run result (ph=X spanning the measured time)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("benchmark", name, ts=_now() - seconds, dur=seconds,
               ph="X")


def emit_marker(name: str, **args) -> None:
    """Free-form instant (benchmark phase boundaries etc.)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("marker", name, **args)


def emit_quality(site: str, **args) -> None:
    """One quality-plane incident (``quality`` kind): a nonzero batch
    of certificate failures, the fixup tier that absorbed them, or an
    IVF q8 exact-scan rerun — result-quality anomalies land on the same
    timeline as the perf events around them (emitted by
    :mod:`raft_tpu.observability.quality`)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("quality", site, **args)


def emit_flow(step: str, rid: int, ph: str = "t",
              outcome: Optional[str] = None, **args) -> None:
    """One per-request flow point (``flow`` kind, Chrome flow-event
    phases): ``ph="s"`` starts request ``rid``'s flow at enqueue,
    ``ph="t"`` steps it through batch assembly / dispatch / requeue on
    the batcher thread, ``ph="f"`` terminates it at completion.
    ``outcome`` annotates the terminus (``ok`` / ``shed`` / ``expired``
    / ``deadline`` / ``reject`` / ``error``). All points share the
    constant event name — Chrome binds flows on (cat, name, id), so
    one request renders as one connected arrow chain across lanes; the
    step label rides in args."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    if outcome is not None:
        args["outcome"] = outcome
    rec.record("flow", "request", ph=ph, flow_id=int(rid), step=step,
               **args)


def emit_mutation(event: str, **args) -> None:
    """One mutable-index write-ahead event (``mutation`` kind). The
    mutation plane's flight stream IS its write-ahead log for
    observability purposes: ``event`` names the step — ``upsert`` /
    ``delete`` (with row counts and the post-apply delta/tombstone
    occupancy), ``compact_start`` / ``compact_swap`` / ``compact_abort``
    (the background fold's lifecycle, with generation numbers) — so a
    Perfetto trace shows every write interleaved with the query
    batches, swaps and deadline scopes around it
    (:mod:`raft_tpu.mutable`)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("mutation", event, lane="mutable", **args)


def emit_serving(event: str, **args) -> None:
    """One serving-engine lifecycle event (``serving`` kind). ``event``
    names the step — ``enqueue`` (request admitted, with queue depth),
    ``flush`` (a coalesced micro-batch dispatched, with bucket/rows),
    ``shed`` (overload admission rejection), ``swap`` (index snapshot
    generation change), ``warmup`` (bucket pre-compile at engine
    start), ``reject`` (request larger than the bucket ladder),
    ``mutate`` (an upsert/delete applied on the batcher) — so a
    Perfetto trace shows the queue → batch → dispatch pipeline next to
    the compile/dispatch/deadline events it feeds."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("serving", event, lane="serving", **args)


def emit_explain(site: str, rid: int, **args) -> None:
    """One per-query explain record (``explain`` kind): the decision
    trail of a sampled live search — chosen plane with its downgrade
    reasons, probed lists, pool width, per-query certificate margins,
    fixup/rerun outcome and per-stage timings — emitted by
    :mod:`raft_tpu.observability.explain` when a capture finalizes, so
    the trace shows WHY a request resolved the way it did next to the
    dispatch/flow events of the same request id."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("explain", site, rid=rid, **args)


def emit_alert(slo: str, severity: str, state: str, **args) -> None:
    """One SLO burn-rate alert transition (``alert`` kind): ``state``
    is ``firing`` (both burn windows over threshold) or ``resolved``
    (recovery cleared it) — emitted by
    :mod:`raft_tpu.observability.slo` so pages line up on the same
    timeline as the sheds/deadlines that caused them."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("alert", slo, severity=severity, state=state,
               lane="slo", **args)


def emit_stall(source: str, **args) -> None:
    """One hang-watchdog stall detection (``stall`` kind): ``source``
    names the silent heartbeat (``serving-batcher``) or the overdue
    request set — emitted by :mod:`raft_tpu.observability.watchdog`
    alongside the thread-stack dump it writes into the blackbox, so a
    postmortem can tell a hang from a violent crash."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("stall", source, lane="watchdog", **args)


def emit_epilogue(reason: str, **args) -> None:
    """The clean-shutdown marker (``epilogue`` kind) the blackbox
    records last: a blackbox file whose newest record is NOT an
    epilogue was a violent death (:mod:`raft_tpu.observability
    .blackbox` reconstructs the verdict from exactly this)."""
    rec = get_flight_recorder()
    if not rec.enabled:
        return
    rec.record("epilogue", reason, lane="lifecycle", **args)


# --------------------------------------------------------- drift ledger
class DriftLedger:
    """Per-site history of (predicted, measured) pairs.

    Thread-safe; bounded to ``max_entries`` per site (newest kept).
    ``record()`` computes ``drift_seconds_ratio`` =
    ``max(pred/meas, meas/pred)`` when both sides are present, emits a
    ``drift`` flight event + registry gauge, and persists when the
    ledger has a ``path`` (atomic tmp+rename — a torn write must not
    corrupt the evidence trail)."""

    def __init__(self, path: Optional[str] = None,
                 max_entries: int = 20):
        self.path = path
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: Dict[str, List[Dict]] = {}

    # -- record -----------------------------------------------------------
    def record(self, site: str,
               predicted_seconds: Optional[float] = None,
               predicted_bytes: Optional[float] = None,
               measured_seconds: Optional[float] = None,
               measured_bytes: Optional[float] = None,
               measured: bool = False, **extra) -> Dict:
        """Append one entry for ``site``; returns it. Never raises into
        the caller's hot path (persistence failures are logged once)."""
        entry: Dict = {
            "predicted_seconds": predicted_seconds,
            "predicted_bytes": predicted_bytes,
            "measured_seconds": measured_seconds,
            "measured_bytes": measured_bytes,
            "measured": bool(measured),
            "ts": time.time(),
        }
        if extra:
            entry.update(extra)
        if (isinstance(predicted_seconds, (int, float))
                and isinstance(measured_seconds, (int, float))
                and predicted_seconds > 0 and measured_seconds > 0):
            r = predicted_seconds / measured_seconds
            entry["drift_seconds_ratio"] = max(r, 1.0 / r)
        with self._lock:
            hist = self._entries.setdefault(site, [])
            hist.append(entry)
            del hist[:-self.max_entries]
        try:
            from raft_tpu.observability.metrics import get_registry

            reg = get_registry()
            reg.counter(DRIFT_RECORDS, {"site": site},
                        help="Drift-ledger entries recorded").inc()
            ratio = entry.get("drift_seconds_ratio")
            if isinstance(ratio, (int, float)):
                reg.gauge(DRIFT_RATIO, {"site": site},
                          help="Latest |model/measured| seconds ratio "
                               "(1.0 = perfect model)").set(ratio)
        except Exception:
            pass
        rec = get_flight_recorder()
        if rec.enabled:
            rec.record("drift", site, measured=bool(measured),
                       predicted_seconds=predicted_seconds,
                       measured_seconds=measured_seconds,
                       ratio=entry.get("drift_seconds_ratio"))
        if self.path:
            self.save()
        return entry

    # -- queries ----------------------------------------------------------
    def entries(self) -> Dict[str, List[Dict]]:
        with self._lock:
            return {k: [dict(e) for e in v]
                    for k, v in self._entries.items()}

    def latest(self, site: str) -> Optional[Dict]:
        with self._lock:
            hist = self._entries.get(site)
            return dict(hist[-1]) if hist else None

    def sites(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._entries.values())

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def merge(self, other: "DriftLedger") -> None:
        """Append ``other``'s per-site histories after this ledger's
        (bounded per site, newest kept) — how a benchmark process folds
        its in-memory entries into the durable repo-root ledger."""
        for site, hist in other.entries().items():
            with self._lock:
                dest = self._entries.setdefault(site, [])
                dest.extend(hist)
                del dest[:-self.max_entries]

    # -- persistence ------------------------------------------------------
    def to_dict(self) -> Dict:
        with self._lock:
            return {"schema": DRIFT_SCHEMA, "band": DRIFT_BAND,
                    "entries": {k: [dict(e) for e in v]
                                for k, v in self._entries.items()}}

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Atomic write; returns the path or None on failure (a ledger
        write must never fail a benchmark)."""
        target = path or self.path
        if not target:
            return None
        try:
            payload = self.to_dict()

            def _write(f):
                json.dump(payload, f, indent=1, sort_keys=True,
                          default=str)
                f.write("\n")

            from raft_tpu.core.diskio import atomic_write

            # tmp + fsync + replace + parent-dir fsync: the bare
            # rename this shipped with could leave an EMPTY file
            # behind the "atomic" swap on power loss
            atomic_write(target, _write, mode="w")
            return target
        except Exception as e:
            from raft_tpu.core.logger import log_warn

            log_warn("drift ledger: could not write %s: %s", target, e)
            return None

    @staticmethod
    def load(path: str, max_entries: int = 20) -> "DriftLedger":
        """Read a ledger file; corrupt/missing degrades to empty (the
        plan-cache contract: a torn evidence file recomputes, never
        raises) — but no longer SILENTLY: every degraded load counts
        under :data:`DRIFT_DEGRADED` with a once-per-process WARN (an
        absent file is the normal cold state, not a degradation)."""
        led = DriftLedger(path=path, max_entries=max_entries)
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return led
        except Exception as e:
            _ledger_degraded(path, "unreadable",
                             f"{type(e).__name__}: {e}")
            return led
        entries = data.get("entries") if isinstance(data, dict) else None
        if not isinstance(entries, dict):
            _ledger_degraded(path, "invalid",
                             "no entries mapping in the payload")
            return led
        try:
            with led._lock:
                for site, hist in entries.items():
                    if isinstance(hist, list):
                        led._entries[str(site)] = [
                            dict(e) for e in hist
                            if isinstance(e, dict)
                        ][-max_entries:]
        except Exception as e:
            _ledger_degraded(path, "invalid",
                             f"{type(e).__name__}: {e}")
        return led


_global_ledger: Optional[DriftLedger] = None
_ledger_lock = threading.Lock()


def get_drift_ledger() -> DriftLedger:
    """Process-global ledger, created lazily; persists automatically
    when env ``RAFT_TPU_DRIFT_LEDGER`` names a path."""
    global _global_ledger
    with _ledger_lock:
        if _global_ledger is None:
            path = os.environ.get("RAFT_TPU_DRIFT_LEDGER", "").strip()
            _global_ledger = DriftLedger(path=path or None)
        return _global_ledger


def set_drift_ledger(ledger: DriftLedger) -> Optional[DriftLedger]:
    """Swap the process-global ledger (tests). Returns the previous."""
    global _global_ledger
    with _ledger_lock:
        prev, _global_ledger = _global_ledger, ledger
        return prev


def record_drift(site: str, **kw) -> Optional[Dict]:
    """Module-level convenience over :meth:`DriftLedger.record` on the
    process-global ledger; respects the tracing kill switch and never
    raises into the measurement path."""
    try:
        from raft_tpu.observability.metrics import tracing_enabled

        if not tracing_enabled():
            return None
        return get_drift_ledger().record(site, **kw)
    except Exception:
        return None
