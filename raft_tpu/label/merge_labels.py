"""Merge two labelings via min-equivalence iteration.

(ref: cpp/include/raft/label/merge_labels.cuh ``merge_labels`` — given two
labelings of the same points (e.g. connected components from two partial
views), iterate label[i] ← min over equivalence classes until fixpoint —
the building block for distributed connected components.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def merge_labels(res, labels_a, labels_b, max_iters: int = 100) -> jax.Array:
    """Return the labeling of the finest common coarsening (each output
    label = min label over the connected equivalence classes induced by
    'same label in a' ∪ 'same label in b'). Labels must be in 0..n-1."""
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    n = a.shape[0]
    out = jnp.minimum(a, b)

    def body(state):
        out, _, it = state
        # propagate min through both partitions
        min_a = jax.ops.segment_min(out, a, num_segments=n)
        out1 = jnp.minimum(out, min_a[a])
        min_b = jax.ops.segment_min(out1, b, num_segments=n)
        out2 = jnp.minimum(out1, min_b[b])
        return out2, jnp.any(out2 != out), it + 1

    def cond(state):
        return state[1] & (state[2] < max_iters)

    out, _, _ = jax.lax.while_loop(
        cond, body, (out, jnp.asarray(True), jnp.asarray(0, jnp.int32)))
    return out
