"""Label compaction utilities.

(ref: cpp/include/raft/label/classlabels.cuh:31 ``getUniquelabels``,
:81,104 ``make_monotonic`` — map arbitrary labels onto 0..n_classes-1;
used to canonicalize cluster/component ids.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def get_unique_labels(res, labels) -> jax.Array:
    """Sorted unique labels. (ref: classlabels.cuh:31 ``getUniquelabels``;
    output size is data-dependent → host step, as the reference allocates
    after a count pass.)"""
    return jnp.asarray(np.unique(np.asarray(labels)))


def make_monotonic(res, labels, classes=None, zero_based: bool = True
                   ) -> Tuple[jax.Array, jax.Array]:
    """Remap labels onto a dense 0..k-1 (or 1..k) range, keeping order.
    Returns (monotonic_labels, classes). (ref: classlabels.cuh:81,104)"""
    labels = jnp.asarray(labels)
    if classes is None:
        classes = get_unique_labels(res, labels)
    # searchsorted requires sorted classes; caller-supplied arrays may not be
    classes = jnp.sort(jnp.asarray(classes))
    mono = jnp.searchsorted(classes, labels).astype(jnp.int32)
    if not zero_based:
        mono = mono + 1
    return mono, classes
