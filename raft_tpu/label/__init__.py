"""raft_tpu.label — label compaction / merging. (ref:
cpp/include/raft/label, SURVEY §2.8.)"""

from raft_tpu.label.classlabels import get_unique_labels, make_monotonic
from raft_tpu.label.merge_labels import merge_labels

__all__ = ["get_unique_labels", "make_monotonic", "merge_labels"]
