"""raft_tpu.random — counter-based RNG + dataset generators. (ref:
cpp/include/raft/random, SURVEY §2.9.)"""

from raft_tpu.random.rng_state import RngState, GeneratorType
from raft_tpu.random.rng import (
    uniform,
    uniform_int,
    normal,
    normal_int,
    normal_table,
    fill,
    lognormal,
    gumbel,
    logistic,
    exponential,
    rayleigh,
    laplace,
    cauchy,
    bernoulli,
    scaled_bernoulli,
    discrete,
    permute,
    sample_without_replacement,
)
from raft_tpu.random.make_blobs import make_blobs
from raft_tpu.random.make_regression import make_regression
from raft_tpu.random.multi_variable_gaussian import (
    multi_variable_gaussian,
    DecompositionMethod,
)
from raft_tpu.random.rmat import rmat_rectangular_gen
