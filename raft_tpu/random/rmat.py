"""R-MAT rectangular graph generator.

(ref: cpp/include/raft/random/rmat_rectangular_generator.cuh + impl
random/detail/rmat_rectangular_generator.cuh; runtime entry
cpp/include/raft_runtime/random/rmat_rectangular_generator.hpp; python
binding python/pylibraft/pylibraft/random/rmat_rectangular_generator.pyx.)

Recursive-matrix generation: each edge picks one of 4 quadrants per scale
level with probabilities (a,b,c,d) — per-level thetas supported like the
reference. TPU-first: all edges × all levels vectorized; levels unroll into
a ``fori_loop`` over bit positions (static trip count = max scale), each
step a categorical draw for every edge simultaneously.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.random.rng_state import _as_key


def rmat_rectangular_gen(
    res,
    state,
    n_edges: int,
    r_scale: int,
    c_scale: int,
    theta=None,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    dtype=jnp.int32,
) -> Tuple[jax.Array, jax.Array]:
    """Generate ``n_edges`` edges of a 2^r_scale × 2^c_scale R-MAT graph.

    ``theta`` may be a flat [4*max(r_scale,c_scale)] per-level quadrant
    probability array (the reference's layout) or None to use (a,b,c,d)
    at every level. Returns (src, dst).
    (ref: rmat_rectangular_generator.cuh ``rmat_rectangular_gen``)
    """
    max_scale = max(r_scale, c_scale)
    if theta is None:
        d = 1.0 - a - b - c
        expects(d >= -1e-6, "rmat: a+b+c must be <= 1")
        theta_arr = jnp.tile(jnp.asarray([a, b, c, max(d, 0.0)], jnp.float32),
                             (max_scale, 1))
    else:
        theta_arr = jnp.asarray(theta, jnp.float32).reshape(max_scale, 4)

    key = _as_key(state)
    # one uniform per (edge, level)
    u = jax.random.uniform(key, (n_edges, max_scale))
    cum = jnp.cumsum(theta_arr, axis=1)  # [levels, 4]
    # quadrant in 0..3 per edge per level: count of cumulative bounds below u
    quad = jnp.sum(u[:, :, None] > cum[None, :, :], axis=-1)
    quad = jnp.clip(quad, 0, 3)
    row_bit = (quad >> 1).astype(dtype)  # quadrant 2,3 → lower half (bit 1)
    col_bit = (quad & 1).astype(dtype)

    # accumulate bits MSB-first over each dimension's own scale
    def accumulate(bits, scale):
        weights = jnp.zeros((max_scale,), dtype).at[:scale].set(
            (2 ** jnp.arange(scale - 1, -1, -1)).astype(dtype))
        return jnp.sum(bits * weights[None, :], axis=1, dtype=dtype)

    src = accumulate(row_bit, r_scale)
    dst = accumulate(col_bit, c_scale)
    return src, dst
