"""Regression dataset generator.

(ref: cpp/include/raft/random/make_regression.cuh — X gaussian, a sparse
informative coefficient vector, y = X·w + bias + noise; optionally returns
the ground-truth coefficients.)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import _as_key


def make_regression(
    res,
    state,
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    dtype=jnp.float32,
):
    """Returns (X, y, coef). y has shape [n_samples] when n_targets==1.
    (ref: make_regression.cuh ``make_regression``)"""
    if n_informative is None:
        n_informative = n_features
    n_informative = min(n_informative, n_features)
    key = _as_key(state)
    kx, kw, kn, klr = jax.random.split(key, 4)
    X = jax.random.normal(kx, (n_samples, n_features), dtype)
    if effective_rank is not None:
        # low-rank covariance structure (ref: detail/make_regression low-rank
        # path): X ← X @ (U diag(s) V^T) with exponentially decaying spectrum
        rank = min(effective_rank, n_features)
        i = jnp.arange(n_features, dtype=dtype)
        s = ((1 - tail_strength) * jnp.exp(-((i / rank) ** 2))
             + tail_strength * jnp.exp(-i / (10.0 * rank)))
        q, _ = jnp.linalg.qr(jax.random.normal(klr, (n_features, n_features), dtype))
        X = X @ (q * s[None, :]) @ q.T
    w = 100.0 * jax.random.uniform(kw, (n_features, n_targets), dtype)
    mask = (jnp.arange(n_features) < n_informative)[:, None]
    w = jnp.where(mask, w, jnp.zeros_like(w))
    y = X @ w + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype)
    if n_targets == 1:
        y = y[:, 0]
        w = w[:, 0]
    return X, y, w
