"""Multivariate gaussian sampling.

(ref: cpp/include/raft/random/multi_variable_gaussian.cuh — samples
x ~ N(mu, Sigma) by factorizing Sigma with Cholesky (or eigendecomposition
via Jacobi for non-PD matrices) and transforming standard normals.)
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import _as_key


class DecompositionMethod(enum.Enum):
    """(ref: multi_variable_gaussian.cuh ``multi_variable_gaussian_decomposition_method``)"""

    CHOLESKY = "cholesky"
    JACOBI = "eig"  # eigendecomposition path


def multi_variable_gaussian(
    res,
    state,
    n_samples: int,
    mu,
    cov,
    method: DecompositionMethod = DecompositionMethod.CHOLESKY,
    dtype=jnp.float32,
):
    """Returns samples [n_samples, dim]. (ref: multi_variable_gaussian.cuh)"""
    mu = jnp.asarray(mu, dtype)
    cov = jnp.asarray(cov, dtype)
    dim = mu.shape[0]
    z = jax.random.normal(_as_key(state), (int(n_samples), dim), dtype)
    if method == DecompositionMethod.CHOLESKY:
        L = jnp.linalg.cholesky(cov)
        samples = z @ L.T
    else:
        w, v = jnp.linalg.eigh(cov)
        w = jnp.maximum(w, 0.0)
        samples = z @ (v * jnp.sqrt(w)[None, :]).T
    return mu[None, :] + samples
