"""Isotropic Gaussian blob generator.

(ref: cpp/include/raft/random/make_blobs.cuh — cluster blobs with optional
given centers, per-cluster std, shuffle; the standard fixture generator for
clustering/knn tests and benchmarks.)

Ground truth is first-class: labels are always returned, ``cluster_std``
may be a per-center array, ``proportions`` produces controllably
IMBALANCED cluster sizes, and ``return_centers=True`` hands back the
true centers — together the controllable oracle the k-means and
IVF-recall suites (tests/test_kmeans.py, tests/test_ivf_flat.py,
benchmarks/bench_ann.py) measure against.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import _as_key


def _imbalanced_labels(n_samples: int, proportions) -> jnp.ndarray:
    """Per-cluster counts from sampling proportions: floor shares with
    the remainder going to the largest-proportion clusters — sizes are
    deterministic for a given (n_samples, proportions), so a test's
    ground-truth histogram is exactly reproducible."""
    import numpy as np

    p = np.asarray(proportions, np.float64)
    if (p < 0).any() or p.sum() <= 0:
        raise ValueError("make_blobs: proportions must be non-negative "
                         "and sum to a positive value")
    p = p / p.sum()
    counts = np.floor(p * n_samples).astype(np.int64)
    short = n_samples - int(counts.sum())
    if short:
        # hand leftover samples to the largest shares, ties by index
        for i in np.argsort(-p, kind="stable")[:short]:
            counts[i] += 1
    return jnp.asarray(np.repeat(np.arange(len(p)), counts),
                       jnp.int32)


def make_blobs(
    res,
    state,
    n_samples: int,
    n_features: int,
    n_clusters: int = 3,
    cluster_std=1.0,
    centers=None,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    shuffle: bool = True,
    proportions=None,
    return_centers: bool = False,
    dtype=jnp.float32,
):
    """Returns ``(X [n_samples, n_features], labels [n_samples])`` —
    or ``(X, labels, centers)`` with ``return_centers=True``.
    (ref: make_blobs.cuh ``make_blobs``)

    - ``cluster_std`` — scalar, or a PER-CENTER array [n_clusters]
      (center ``i``'s points get std ``cluster_std[i]``).
    - ``proportions`` — per-cluster sampling proportions [n_clusters]
      switching on the IMBALANCED-sizes mode (deterministic counts:
      floor shares + remainder to the largest); default None keeps the
      reference's balanced round-robin assignment.
    """
    key = _as_key(state)
    k_centers, k_labels, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype,
            minval=center_box[0], maxval=center_box[1])
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    if proportions is not None:
        if len(proportions) != n_clusters:
            raise ValueError(
                f"make_blobs: proportions has {len(proportions)} "
                f"entries for {n_clusters} clusters")
        labels = _imbalanced_labels(n_samples, proportions)
    else:
        # balanced assignment like the reference (round-robin), then
        # shuffle
        labels = jnp.arange(n_samples, dtype=jnp.int32) % n_clusters
    std = jnp.asarray(cluster_std, dtype)
    per_point_std = std[labels] if std.ndim == 1 else std
    noise = jax.random.normal(k_noise, (n_samples, n_features), dtype)
    X = centers[labels] + noise * (
        per_point_std[:, None] if getattr(per_point_std, "ndim", 0) else per_point_std
    )
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        X, labels = X[perm], labels[perm]
    if return_centers:
        return X, labels, centers
    return X, labels
