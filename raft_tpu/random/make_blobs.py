"""Isotropic Gaussian blob generator.

(ref: cpp/include/raft/random/make_blobs.cuh — cluster blobs with optional
given centers, per-cluster std, shuffle; the standard fixture generator for
clustering/knn tests and benchmarks.)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import _as_key


def make_blobs(
    res,
    state,
    n_samples: int,
    n_features: int,
    n_clusters: int = 3,
    cluster_std=1.0,
    centers=None,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    shuffle: bool = True,
    dtype=jnp.float32,
):
    """Returns (X [n_samples, n_features], labels [n_samples]).
    (ref: make_blobs.cuh ``make_blobs``)"""
    key = _as_key(state)
    k_centers, k_labels, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype,
            minval=center_box[0], maxval=center_box[1])
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    # balanced assignment like the reference (round-robin), then shuffle
    labels = jnp.arange(n_samples, dtype=jnp.int32) % n_clusters
    std = jnp.asarray(cluster_std, dtype)
    per_point_std = std[labels] if std.ndim == 1 else std
    noise = jax.random.normal(k_noise, (n_samples, n_features), dtype)
    X = centers[labels] + noise * (
        per_point_std[:, None] if getattr(per_point_std, "ndim", 0) else per_point_std
    )
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        X, labels = X[perm], labels[perm]
    return X, labels
