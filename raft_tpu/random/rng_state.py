"""RNG state vocabulary.

(ref: cpp/include/raft/random/rng_state.hpp:19-40 — ``GeneratorType{GenPhilox,
GenPC}`` (default PCG) and ``RngState{seed, base_subsequence, type}``; device
generators in random/detail/rng_device.cuh:426,536; PCG reference impl in
thirdparty/pcg/pcg_basic.c.)

TPU-native mapping (SURVEY §2.9): counter-based threefry is JAX's native
generator, the exact analog of Philox on CUDA — ``RngState`` becomes a seed +
subsequence folded into a ``jax.random`` key. THREEFRY is the default and
the high-throughput choice on TPU; PCG32 is also provided (host-side, via
the C++ hostops library when built, else a pure-python fallback) for
reference-compatible stream semantics where bit-level reproducibility
against PCG matters.
"""

from __future__ import annotations

import enum

import jax


class GeneratorType(enum.Enum):
    """(ref: rng_state.hpp:19 ``GeneratorType``)"""

    THREEFRY = "threefry"  # TPU-native default (counter-based, like Philox)
    PHILOX = "threefry"    # alias: JAX's counter-based PRNG plays this role
    PCG = "pcg"            # host-side PCG32 stream (bit-compatible layout)


class RngState:
    """(ref: rng_state.hpp:29 ``RngState{seed, base_subsequence, type}``)"""

    def __init__(self, seed: int = 0, base_subsequence: int = 0,
                 type: GeneratorType = GeneratorType.THREEFRY):  # noqa: A002
        self.seed = int(seed)
        self.base_subsequence = int(base_subsequence)
        self.type = type

    def key(self) -> jax.Array:
        """The jax PRNG key for this state (seed ⊕ subsequence via fold_in)."""
        k = jax.random.key(self.seed)
        if self.base_subsequence:
            k = jax.random.fold_in(k, self.base_subsequence)
        return k

    def advance(self, n_subsequences: int = 1) -> "RngState":
        """Advance the stream. (ref: rng_state.hpp ``advance``)"""
        self.base_subsequence += int(n_subsequences)
        return self

    def split(self) -> "RngState":
        """A fresh state on an independent subsequence (functional helper)."""
        self.advance()
        return RngState(self.seed, self.base_subsequence, self.type)

    def __repr__(self):
        return (f"RngState(seed={self.seed}, "
                f"base_subsequence={self.base_subsequence}, type={self.type.name})")


def _as_key(state_or_key):
    """Accept RngState, a jax key, or an int seed. A PCG-typed state is
    refused here: only ``uniform`` implements the PCG stream, and silently
    substituting threefry would break the bit-parity contract."""
    if isinstance(state_or_key, RngState):
        if state_or_key.type == GeneratorType.PCG:
            raise NotImplementedError(
                "GeneratorType.PCG is only supported by random.uniform(); "
                "use THREEFRY for other distributions")
        return state_or_key.key()
    if isinstance(state_or_key, int):
        return jax.random.key(state_or_key)
    return state_or_key
