"""Device RNG distributions.

(ref: cpp/include/raft/random/rng.cuh + random/detail/rng_impl.cuh — uniform/
uniformInt/normal/normalInt/lognormal/gumbel/logistic/exponential/rayleigh/
laplace/cauchy/bernoulli/scaled_bernoulli/discrete/fill;
sample_without_replacement in random/sample_without_replacement.cuh; permute
in random/permute.cuh.)

All functions take an ``RngState`` / jax key / int seed as the stream
argument and are pure: same state → same output (counter-based threefry
underneath, the TPU-native generator).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.random.rng_state import RngState, _as_key


def uniform(res, state, shape, low=0.0, high=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``uniform``). An ``RngState`` with
    ``GeneratorType.PCG`` draws from the reference-compatible PCG32 stream
    (native hostops library; ref: thirdparty/pcg/pcg_basic.c) — for
    bit-level stream parity with the reference's default generator."""
    from raft_tpu.random.rng_state import GeneratorType, RngState

    if isinstance(state, RngState) and state.type == GeneratorType.PCG:
        from raft_tpu import native

        n = 1
        for s in shape:
            n *= s
        u = native.pcg32_uniform(state.seed, n, stream=state.base_subsequence)
        return (jnp.asarray(u.reshape(tuple(shape)), dtype) * (high - low) + low)
    return jax.random.uniform(_as_key(state), tuple(shape), dtype, low, high)


def uniform_int(res, state, shape, low, high, dtype=jnp.int32):
    """(ref: rng.cuh ``uniformInt``; [low, high) as in the reference)"""
    return jax.random.randint(_as_key(state), tuple(shape), low, high, dtype)


def normal(res, state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``normal``)"""
    return mu + sigma * jax.random.normal(_as_key(state), tuple(shape), dtype)


def normal_int(res, state, shape, mu, sigma, dtype=jnp.int32):
    """(ref: rng.cuh ``normalInt`` — rounded normal)"""
    return jnp.round(normal(res, state, shape, mu, sigma)).astype(dtype)


def normal_table(res, state, n_rows, mu_vec, sigma_vec=None, sigma=1.0,
                 dtype=jnp.float32):
    """Each column j ~ N(mu_vec[j], sigma_vec[j]). (ref: rng.cuh
    ``normalTable``)"""
    mu_vec = jnp.asarray(mu_vec)
    n_cols = mu_vec.shape[0]
    z = jax.random.normal(_as_key(state), (int(n_rows), int(n_cols)), dtype)
    s = jnp.asarray(sigma_vec)[None, :] if sigma_vec is not None else sigma
    return mu_vec[None, :] + z * s


def fill(res, state, shape, value, dtype=jnp.float32):
    """(ref: rng.cuh ``fill``)"""
    return jnp.full(tuple(shape), value, dtype=dtype)


def lognormal(res, state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``lognormal``)"""
    return jnp.exp(normal(res, state, shape, mu, sigma, dtype))


def gumbel(res, state, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``gumbel``)"""
    return mu + beta * jax.random.gumbel(_as_key(state), tuple(shape), dtype)


def logistic(res, state, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``logistic``)"""
    return mu + scale * jax.random.logistic(_as_key(state), tuple(shape), dtype)


def exponential(res, state, shape, lambda_=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``exponential``; rate parameterization)"""
    return jax.random.exponential(_as_key(state), tuple(shape), dtype) / lambda_


def rayleigh(res, state, shape, sigma=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``rayleigh``)"""
    u = jax.random.uniform(_as_key(state), tuple(shape), dtype,
                           minval=jnp.finfo(dtype).tiny, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(res, state, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``laplace``)"""
    return mu + scale * jax.random.laplace(_as_key(state), tuple(shape), dtype)


def cauchy(res, state, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    """(ref: rng.cuh ``cauchy``)"""
    return mu + scale * jax.random.cauchy(_as_key(state), tuple(shape), dtype)


def bernoulli(res, state, shape, prob=0.5):
    """(ref: rng.cuh ``bernoulli``)"""
    return jax.random.bernoulli(_as_key(state), prob, tuple(shape))


def scaled_bernoulli(res, state, shape, prob=0.5, scale=1.0, dtype=jnp.float32):
    """Draws in {-scale, +scale} with P(-scale) = prob, matching the
    reference (detail/rng_device.cuh: ``res < prob ? -scale : scale``)."""
    b = jax.random.bernoulli(_as_key(state), prob, tuple(shape))
    return jnp.where(b, jnp.asarray(-scale, dtype), jnp.asarray(scale, dtype))


def discrete(res, state, shape, weights, dtype=jnp.int32):
    """Categorical sampling by unnormalized weights.
    (ref: rng.cuh ``discrete``)"""
    weights = jnp.asarray(weights, jnp.float32)
    logits = jnp.log(jnp.where(weights > 0, weights, jnp.finfo(jnp.float32).tiny))
    return jax.random.categorical(_as_key(state), logits, shape=tuple(shape)).astype(dtype)


def permute(res, state, matrix=None, n: Optional[int] = None):
    """Random row permutation. Returns (perm, permuted_matrix|None).
    (ref: random/permute.cuh ``permute`` — outputs the permutation vector
    and optionally the row-shuffled matrix.)"""
    expects(matrix is not None or n is not None, "permute: need matrix or n")
    if matrix is not None:
        matrix = jnp.asarray(matrix)
        n = matrix.shape[0]
    perm = jax.random.permutation(_as_key(state), n)
    out = matrix[perm, :] if matrix is not None else None
    return perm.astype(jnp.int32), out


def sample_without_replacement(res, state, population: int, n_samples: int,
                               weights=None, dtype=jnp.int32):
    """Weighted sampling without replacement via Gumbel top-k (the
    TPU-idiomatic one-shot algorithm; the reference does a device-side
    weighted reservoir — random/sample_without_replacement.cuh).
    Returns sampled indices."""
    expects(n_samples <= population,
            "sample_without_replacement: n_samples %d > population %d",
            n_samples, population)
    key = _as_key(state)
    if weights is None:
        return jax.random.choice(key, population, shape=(n_samples,),
                                 replace=False).astype(dtype)
    w = jnp.asarray(weights, jnp.float32)
    logits = jnp.log(jnp.where(w > 0, w, jnp.finfo(jnp.float32).tiny))
    g = logits + jax.random.gumbel(key, (population,))
    _, idx = jax.lax.top_k(g, n_samples)
    return idx.astype(dtype)
