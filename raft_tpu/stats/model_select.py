"""Dispersion and information criteria.

(ref: cpp/include/raft/stats/dispersion.cuh — between-cluster dispersion
from centroids + cluster sizes; stats/information_criterion.cuh — batched
AIC/AICc/BIC from log-likelihoods.)
"""

from __future__ import annotations

import enum
from typing import Optional

import jax.numpy as jnp


def dispersion(res, centroids, cluster_sizes, global_centroid=None,
               n_points: Optional[int] = None) -> float:
    """sqrt(Σ_k n_k ‖μ_k − μ‖²) — the between-group dispersion used by
    e.g. the gap statistic. (ref: stats/dispersion.cuh ``dispersion``
    — returns the sqrt of accumulated weighted squared deviations.)"""
    centroids = jnp.asarray(centroids)
    sizes = jnp.asarray(cluster_sizes, centroids.dtype)
    if n_points is None:
        n_points = float(jnp.sum(sizes))
    if global_centroid is None:
        global_centroid = (sizes[:, None] * centroids).sum(axis=0) / n_points
    g = jnp.asarray(global_centroid)
    dev = centroids - g[None, :]
    return float(jnp.sqrt(jnp.sum(sizes * jnp.sum(dev * dev, axis=1))))


class IC_Type(enum.Enum):
    """(ref: stats/information_criterion.cuh ``IC_Type``)"""

    AIC = "aic"
    AICc = "aicc"
    BIC = "bic"


def information_criterion_batched(res, loglikelihood, ic_type: IC_Type,
                                  n_params: int, batch_size: int,
                                  n_samples: int):
    """Batched AIC/AICc/BIC. (ref: stats/information_criterion.cuh
    ``information_criterion_batched``)"""
    ll = jnp.asarray(loglikelihood, jnp.float32)
    p = float(n_params)
    n = float(n_samples)
    base = -2.0 * ll
    if ic_type == IC_Type.AIC:
        return base + 2.0 * p
    if ic_type == IC_Type.AICc:
        return base + 2.0 * p + 2.0 * p * (p + 1.0) / jnp.maximum(n - p - 1.0, 1e-30)
    return base + p * jnp.log(n)
