"""Histogram.

(ref: cpp/include/raft/stats/histogram.cuh + detail/histogram.cuh (487 LoC,
multi-strategy: global-atomics / shared-memory variants picked by
``HistType``). On TPU there are no atomics; the one strategy that maps well
is binning + segment-sum (sorted scatter-add), which XLA schedules
efficiently — the HistType enum is kept for API parity and ignored.)
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp


class HistType(enum.Enum):
    """(ref: stats/histogram.cuh ``HistType`` — strategy hints; one TPU
    strategy serves all.)"""

    Auto = "auto"
    GlobalAtomics = "auto"
    SmemBits = "auto"


class IdentityBinner:
    """(ref: stats/histogram.cuh ``IdentityBinner`` — data are bin ids)"""

    def __call__(self, x, row):
        return x.astype(jnp.int32)


def histogram(res, data, n_bins: int, binner: Optional[Callable] = None,
              hist_type: HistType = HistType.Auto):
    """Batched histogram: data [n, batch] → counts [n_bins, batch].
    1-D input gives [n_bins]. (ref: stats/histogram.cuh ``histogram`` —
    same column-batched layout.)"""
    data = jnp.asarray(data)
    one_d = data.ndim == 1
    if one_d:
        data = data[:, None]
    if binner is None:
        binner = IdentityBinner()
    cols = jnp.arange(data.shape[1])
    bins = binner(data, cols[None, :])
    bins = jnp.clip(bins, 0, n_bins - 1)

    def col_hist(b):
        return jnp.bincount(b, length=n_bins)

    out = jax.vmap(col_hist, in_axes=1, out_axes=1)(bins)
    return out[:, 0] if one_d else out


def value_histogram(res, values, n_bins: int, lo=None, hi=None):
    """Convenience equal-width binning over a value range."""
    values = jnp.asarray(values)
    lo = jnp.min(values) if lo is None else lo
    hi = jnp.max(values) if hi is None else hi
    width = jnp.maximum((hi - lo) / n_bins, 1e-30)
    bins = jnp.clip(((values - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    return histogram(res, bins, n_bins)
