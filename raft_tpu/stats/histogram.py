"""Histogram — multi-strategy, like the reference.

(ref: cpp/include/raft/stats/histogram.cuh + detail/histogram.cuh (487
LoC): ``HistType`` selects among global-atomics and shared-memory-bits
strategies. TPU has no atomics; the strategy space re-designed TPU-first:

- ``SegmentSum`` — binning + ``bincount`` (XLA sorted scatter-add): the
  general path, any n_bins, the global-atomics role.
- ``OneHot`` — row-chunked one-hot compare + reduce, pure dense VPU work,
  no scatter at all; wins when n_bins is small enough that the
  [chunk, n_bins, batch] compare is cheaper than a scatter pass.
- ``Blocked`` — the Pallas VMEM-accumulator kernel
  (raft_tpu.ops.histogram_pallas): the smem-histogram role — the
  [n_bins, batch] counter block stays resident in VMEM across the
  row-block grid.

``Auto`` mirrors the reference's selection heuristic mechanism with a
TPU rule: small bin spaces take the dense strategies (Blocked on TPU,
OneHot elsewhere), everything else SegmentSum.)
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp


class HistType(enum.Enum):
    """(ref: stats/histogram.cuh ``HistType`` — strategy selector; the
    legacy names alias their TPU role-equivalents.)"""

    Auto = "auto"
    SegmentSum = "segment_sum"
    OneHot = "one_hot"
    Blocked = "blocked"
    # reference-name compat aliases
    GlobalAtomics = "segment_sum"
    SmemBits = "blocked"


class IdentityBinner:
    """(ref: stats/histogram.cuh ``IdentityBinner`` — data are bin ids)"""

    def __call__(self, x, row):
        return x.astype(jnp.int32)


# dense strategies hold an [n_bins, chunk-or-SUB, batch] one-hot temp;
# past this bin count the scatter path wins (measured envelope, see
# benchmarks/bench_prims.py histogram rows)
_DENSE_MAX_BINS = 1024
# Blocked kernel VMEM budget for the one-hot temp + accumulator + input
# block; past it Mosaic would fail to fit the kernel
_BLOCKED_VMEM_BYTES = 4 << 20


def _choose_hist_type(n: int, batch: int, n_bins: int) -> HistType:
    """(ref: detail/histogram.cuh strategy pick; TPU rule.)"""
    if batch == 1:
        # 1-D (the value_histogram ravel path): the dense strategies use
        # 1 of 128 lanes; XLA's fused bincount handles this shape well
        return HistType.SegmentSum
    if n_bins <= _DENSE_MAX_BINS:
        from raft_tpu.ops.histogram_pallas import _SUB

        fits_vmem = (n_bins * batch * (_SUB + 2) * 4 + 1024 * batch * 4
                     <= _BLOCKED_VMEM_BYTES)
        if jax.default_backend() == "tpu" and n >= 4096 and fits_vmem:
            return HistType.Blocked
        return HistType.OneHot
    return HistType.SegmentSum


def _hist_segment_sum(bins, n_bins: int):
    def col_hist(b):
        return jnp.bincount(b, length=n_bins)

    return jax.vmap(col_hist, in_axes=1, out_axes=1)(bins)


def _hist_one_hot(bins, n_bins: int, chunk: Optional[int] = None):
    """Row-chunked dense count: counts[b, c] = Σ_r [bins[r, c] = b]."""
    n, batch = bins.shape
    if chunk is None:
        # bound the [n_bins, chunk, batch] compare temp to ~16 MB int32
        chunk = max(8, min(2048, (1 << 22) // max(n_bins * batch, 1)))
    pad = (-n) % chunk
    if pad:  # pad id -1 matches no bin
        bins = jnp.concatenate([bins, jnp.full((pad, batch), -1, jnp.int32)])
    blocks = bins.reshape(-1, chunk, batch)
    ids = jnp.arange(n_bins, dtype=jnp.int32)[:, None, None]

    def body(carry, blk):
        onehot = (blk[None, :, :] == ids).astype(jnp.int32)
        return carry + jnp.sum(onehot, axis=1), None

    init = jnp.zeros((n_bins, batch), jnp.int32)
    counts, _ = jax.lax.scan(body, init, blocks)
    return counts


def histogram(res, data, n_bins: int, binner: Optional[Callable] = None,
              hist_type: HistType = HistType.Auto):
    """Batched histogram: data [n, batch] → counts [n_bins, batch].
    1-D input gives [n_bins]. (ref: stats/histogram.cuh ``histogram`` —
    same column-batched layout and strategy-enum contract.)"""
    data = jnp.asarray(data)
    one_d = data.ndim == 1
    if one_d:
        data = data[:, None]
    if binner is None:
        binner = IdentityBinner()
    cols = jnp.arange(data.shape[1])
    bins = binner(data, cols[None, :])
    bins = jnp.clip(bins, 0, n_bins - 1)

    ht = hist_type
    if ht is HistType.Auto:
        ht = _choose_hist_type(bins.shape[0], bins.shape[1], n_bins)
    if ht.value == "blocked":
        from raft_tpu.ops.histogram_pallas import histogram_blocked

        out = histogram_blocked(bins, n_bins)
    elif ht.value == "one_hot":
        out = _hist_one_hot(bins, n_bins)
    else:
        out = _hist_segment_sum(bins, n_bins)
    return out[:, 0] if one_d else out


def value_histogram(res, values, n_bins: int, lo=None, hi=None):
    """Convenience equal-width binning over a value range."""
    values = jnp.asarray(values)
    lo = jnp.min(values) if lo is None else lo
    hi = jnp.max(values) if hi is None else hi
    width = jnp.maximum((hi - lo) / n_bins, 1e-30)
    bins = jnp.clip(((values - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    return histogram(res, bins, n_bins)
