"""raft_tpu.stats — statistics & model metrics. (ref:
cpp/include/raft/stats, SURVEY §2.10.)"""

from raft_tpu.stats.moments import (
    sum_stat,
    mean,
    mean_center,
    mean_add,
    vars_,
    stddev,
    meanvar,
    weighted_mean,
    cov,
    minmax,
)
from raft_tpu.stats.histogram import (
    HistType,
    IdentityBinner,
    histogram,
    value_histogram,
)
from raft_tpu.stats.metrics import (
    accuracy,
    r2_score,
    RegressionMetrics,
    regression_metrics,
    mean_squared_error,
)
from raft_tpu.stats.cluster import (
    contingency_matrix,
    get_contingency_matrix_shape,
    rand_index,
    adjusted_rand_index,
    entropy,
    mutual_info_score,
    homogeneity_score,
    completeness_score,
    v_measure,
    kl_divergence,
)
from raft_tpu.stats.embed import (
    silhouette_score,
    silhouette_score_batched,
    trustworthiness_score,
    neighborhood_recall,
)
from raft_tpu.stats.model_select import (
    dispersion,
    IC_Type,
    information_criterion_batched,
)
