"""Statistical moments.

(ref: cpp/include/raft/stats/ — mean.cuh, mean_center.cuh (center/add),
stddev.cuh, vars.cuh, meanvar.cuh (detail/meanvar.cuh 222), sum.cuh,
weighted_mean.cuh (row/col variants), cov.cuh (gemm-based), minmax.cuh
(detail/minmax.cuh 228).)

Convention: like the reference, reductions are over rows by default —
one statistic per column — with ``sample`` selecting the n−1 normalizer.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects


def sum_stat(res, data, along_rows: bool = True):
    """(ref: stats/sum.cuh ``sum``)"""
    return jnp.sum(jnp.asarray(data), axis=0 if along_rows else 1)


def mean(res, data, sample: bool = False):
    """Column means. (ref: stats/mean.cuh; ``sample`` divides by n-1)

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.stats import mean
    >>> np.asarray(mean(None, np.array([[1.0, 2.0], [3.0, 4.0]]))).tolist()
    [2.0, 3.0]
    """
    data = jnp.asarray(data)
    n = data.shape[0]
    denom = (n - 1) if sample else n
    return jnp.sum(data, axis=0) / denom


def mean_center(res, data, mu=None):
    """(ref: stats/mean_center.cuh ``meanCenter``)"""
    data = jnp.asarray(data)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    return data - jnp.asarray(mu)[None, :]


def mean_add(res, data, mu):
    """(ref: stats/mean_center.cuh ``meanAdd``)"""
    return jnp.asarray(data) + jnp.asarray(mu)[None, :]


def vars_(res, data, mu=None, sample: bool = False):
    """Column variances. (ref: stats/vars.cuh ``vars``)"""
    data = jnp.asarray(data)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    mu = jnp.asarray(mu)
    n = data.shape[0]
    denom = (n - 1) if sample else n
    return jnp.sum((data - mu[None, :]) ** 2, axis=0) / denom


def stddev(res, data, mu=None, sample: bool = False):
    """(ref: stats/stddev.cuh)"""
    return jnp.sqrt(vars_(res, data, mu, sample))


def meanvar(res, data, sample: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused mean+variance. (ref: stats/meanvar.cuh — single-pass kernel;
    XLA fuses the two reductions the same way.)"""
    data = jnp.asarray(data)
    mu = jnp.mean(data, axis=0)
    return mu, vars_(res, data, mu, sample)


def weighted_mean(res, data, weights, along_rows: bool = True):
    """Weighted mean. ``along_rows=True`` averages over rows (one value per
    column, weights sized n_rows). (ref: stats/weighted_mean.cuh
    ``rowWeightedMean``/``colWeightedMean``)"""
    data = jnp.asarray(data)
    w = jnp.asarray(weights)
    if along_rows:
        expects(w.shape[0] == data.shape[0], "weighted_mean: weight length")
        return (w[:, None] * data).sum(axis=0) / w.sum()
    expects(w.shape[0] == data.shape[1], "weighted_mean: weight length")
    return (data * w[None, :]).sum(axis=1) / w.sum()


def cov(res, data, mu=None, sample: bool = True, stable: bool = False):
    """Covariance matrix of rows-as-observations. (ref: stats/cov.cuh —
    gemm-based; ``stable`` recenters explicitly first like the reference.)"""
    data = jnp.asarray(data)
    n = data.shape[0]
    if mu is None:
        mu = jnp.mean(data, axis=0)
    mu = jnp.asarray(mu)
    denom = (n - 1) if sample else n
    if stable:
        c = data - mu[None, :]
        return jnp.matmul(c.T, c, preferred_element_type=jnp.float32) / denom
    g = jnp.matmul(data.T, data, preferred_element_type=jnp.float32)
    return (g - n * jnp.outer(mu, mu)) / denom


def minmax(res, data) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-column (min, max). (ref: stats/minmax.cuh)"""
    data = jnp.asarray(data)
    return jnp.min(data, axis=0), jnp.max(data, axis=0)
