"""Classification / regression metrics.

(ref: cpp/include/raft/stats/ — accuracy.cuh, r2_score.cuh,
regression_metrics.cuh, mean_squared_error.cuh.)
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from raft_tpu.linalg.reduce import mean_squared_error  # re-export (ref: stats/mean_squared_error.cuh)  # noqa: F401


def accuracy(res, predictions, ref_predictions) -> float:
    """Fraction of exact matches. (ref: stats/accuracy.cuh
    ``accuracy_score``)"""
    p = jnp.asarray(predictions)
    r = jnp.asarray(ref_predictions)
    return float(jnp.mean((p == r).astype(jnp.float32)))


def r2_score(res, y, y_hat) -> float:
    """(ref: stats/r2_score.cuh)"""
    y = jnp.asarray(y)
    y_hat = jnp.asarray(y_hat)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return float(1.0 - ss_res / ss_tot)


class RegressionMetrics(NamedTuple):
    """(ref: stats/regression_metrics.cuh out params)"""

    mean_abs_error: float
    mean_squared_error: float
    median_abs_error: float


def regression_metrics(res, predictions, ref_predictions) -> RegressionMetrics:
    """(ref: stats/regression_metrics.cuh ``regression_metrics``)"""
    p = jnp.asarray(predictions, jnp.float32)
    r = jnp.asarray(ref_predictions, jnp.float32)
    err = p - r
    return RegressionMetrics(
        float(jnp.mean(jnp.abs(err))),
        float(jnp.mean(err * err)),
        float(jnp.median(jnp.abs(err))),
    )
