"""Clustering comparison metrics.

(ref: cpp/include/raft/stats/ — contingency_matrix.cuh
(detail/contingencyMatrix.cuh 305), adjusted_rand_index.cuh
(detail/adjusted_rand_index.cuh 196), rand_index.cuh,
mutual_info_score.cuh, entropy.cuh, completeness_score.cuh,
homogeneity_score.cuh, v_measure.cuh, kl_divergence.cuh.)

All are built from one contingency matrix the way the reference builds
them; values match sklearn's definitions (which the reference tests
against).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def get_contingency_matrix_shape(res, a, b) -> Tuple[int, int]:
    """(ref: contingency_matrix.cuh ``getContingencyMatrixWorkspaceSize``
    companion — bins are 0..max)"""
    import numpy as np

    return int(np.asarray(a).max()) + 1, int(np.asarray(b).max()) + 1


def contingency_matrix(res, a, b, n_classes_a: Optional[int] = None,
                       n_classes_b: Optional[int] = None):
    """Counts[ i, j ] = |{k : a[k]=i ∧ b[k]=j}|.
    (ref: stats/contingency_matrix.cuh ``contingency_matrix``)"""
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    if n_classes_a is None or n_classes_b is None:
        ca, cb = get_contingency_matrix_shape(res, a, b)
        n_classes_a = n_classes_a or ca
        n_classes_b = n_classes_b or cb
    flat = a * n_classes_b + b
    counts = jnp.bincount(flat, length=n_classes_a * n_classes_b)
    return counts.reshape(n_classes_a, n_classes_b)


def _comb2(x):
    return x * (x - 1) / 2.0


def rand_index(res, a, b) -> float:
    """(ref: stats/rand_index.cuh ``rand_index``)"""
    cm = contingency_matrix(res, a, b).astype(jnp.float64 if jax.config.x64_enabled else jnp.float32)
    n = jnp.sum(cm)
    sum_sq = jnp.sum(cm * cm)
    sum_rows_sq = jnp.sum(jnp.sum(cm, axis=1) ** 2)
    sum_cols_sq = jnp.sum(jnp.sum(cm, axis=0) ** 2)
    # pairs agreeing: same-same + diff-diff
    agree = _comb2(n) + sum_sq - 0.5 * (sum_rows_sq + sum_cols_sq)
    return float(agree / _comb2(n))


def adjusted_rand_index(res, a, b) -> float:
    """(ref: stats/adjusted_rand_index.cuh)"""
    cm = contingency_matrix(res, a, b).astype(jnp.float32)
    n = jnp.sum(cm)
    sum_comb = jnp.sum(_comb2(cm))
    comb_a = jnp.sum(_comb2(jnp.sum(cm, axis=1)))
    comb_b = jnp.sum(_comb2(jnp.sum(cm, axis=0)))
    expected = comb_a * comb_b / _comb2(n)
    max_index = 0.5 * (comb_a + comb_b)
    denom = max_index - expected
    if float(denom) == 0.0:
        return 1.0
    return float((sum_comb - expected) / denom)


def entropy(res, labels, n_classes: Optional[int] = None) -> float:
    """Shannon entropy of a labeling (nats). (ref: stats/entropy.cuh)"""
    labels = jnp.asarray(labels, jnp.int32)
    if n_classes is None:
        import numpy as np

        n_classes = int(np.asarray(labels).max()) + 1
    counts = jnp.bincount(labels, length=n_classes).astype(jnp.float32)
    p = counts / counts.sum()
    return float(-jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0)))


def mutual_info_score(res, a, b) -> float:
    """(ref: stats/mutual_info_score.cuh)"""
    cm = contingency_matrix(res, a, b).astype(jnp.float32)
    n = jnp.sum(cm)
    pij = cm / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = jnp.where(pij > 0, pij / (pi * pj), 1.0)
    return float(jnp.sum(jnp.where(pij > 0, pij * jnp.log(ratio), 0.0)))


def homogeneity_score(res, truth, pred) -> float:
    """(ref: stats/homogeneity_score.cuh) 1 − H(C|K)/H(C)."""
    h_c = entropy(res, truth)
    if h_c == 0.0:
        return 1.0
    mi = mutual_info_score(res, truth, pred)
    return mi / h_c


def completeness_score(res, truth, pred) -> float:
    """(ref: stats/completeness_score.cuh) 1 − H(K|C)/H(K)."""
    h_k = entropy(res, pred)
    if h_k == 0.0:
        return 1.0
    mi = mutual_info_score(res, truth, pred)
    return mi / h_k


def v_measure(res, truth, pred, beta: float = 1.0) -> float:
    """(ref: stats/v_measure.cuh)"""
    h = homogeneity_score(res, truth, pred)
    c = completeness_score(res, truth, pred)
    if h + c == 0.0:
        return 0.0
    return (1 + beta) * h * c / (beta * h + c)


def kl_divergence(res, p, q) -> float:
    """Σ p log(p/q) over two distributions. (ref: stats/kl_divergence.cuh)"""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    ratio = jnp.where((p > 0) & (q > 0), p / jnp.where(q > 0, q, 1.0), 1.0)
    return float(jnp.sum(jnp.where(p > 0, p * jnp.log(ratio), 0.0)))
