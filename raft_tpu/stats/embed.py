"""Embedding-quality metrics: silhouette, trustworthiness, neighborhood
recall.

(ref: cpp/include/raft/stats/silhouette_score.cuh:37 (+ batched variant
detail/batched/silhouette_score.cuh — computes its own pairwise distances
internally), trustworthiness_score (detail/trustworthiness_score.cuh 211,
takes precomputed knn indices), neighborhood_recall
(detail/neighborhood_recall.cuh).)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.pairwise import pairwise_distance


def silhouette_score(res, X, labels, n_clusters: Optional[int] = None,
                     metric: str = "sqeuclidean") -> float:
    """Mean silhouette coefficient. (ref: stats/silhouette_score.cuh:37)"""
    X = jnp.asarray(X)
    labels = jnp.asarray(labels, jnp.int32)
    n = X.shape[0]
    if n_clusters is None:
        import numpy as np

        n_clusters = int(np.asarray(labels).max()) + 1
    D = pairwise_distance(res, X, X, metric=metric)
    onehot = jax.nn.one_hot(labels, n_clusters, dtype=D.dtype)  # [n, k]
    cluster_sizes = jnp.sum(onehot, axis=0)                     # [k]
    # mean distance of point i to each cluster: [n, k]
    sums = D @ onehot
    own = labels
    own_size = cluster_sizes[own]
    # a(i): mean intra-cluster distance excluding self (D[ii]=0)
    a = jnp.where(own_size > 1,
                  jnp.take_along_axis(sums, own[:, None], axis=1)[:, 0]
                  / jnp.maximum(own_size - 1, 1), 0.0)
    # b(i): min over other clusters of mean distance
    means = sums / jnp.maximum(cluster_sizes[None, :], 1)
    means = jnp.where(cluster_sizes[None, :] > 0, means, jnp.inf)
    means = means.at[jnp.arange(n), own].set(jnp.inf)
    b = jnp.min(means, axis=1)
    s = jnp.where(own_size > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return float(jnp.mean(s))


def silhouette_score_batched(res, X, labels, n_clusters: Optional[int] = None,
                             metric: str = "sqeuclidean",
                             chunk: int = 1024) -> float:
    """Tiled variant that never materializes the full n×n distance matrix.
    (ref: detail/batched/silhouette_score.cuh)"""
    X = jnp.asarray(X)
    labels = jnp.asarray(labels, jnp.int32)
    n = X.shape[0]
    if n_clusters is None:
        import numpy as np

        n_clusters = int(np.asarray(labels).max()) + 1
    onehot = jax.nn.one_hot(labels, n_clusters, dtype=X.dtype)
    cluster_sizes = jnp.sum(onehot, axis=0)
    total = jnp.zeros((), X.dtype)  # device accumulator: chunks stay async
    for start in range(0, n, chunk):
        Xc = X[start:start + chunk]
        lc = labels[start:start + chunk]
        D = pairwise_distance(res, Xc, X, metric=metric)
        sums = D @ onehot
        own_size = cluster_sizes[lc]
        a = jnp.where(own_size > 1,
                      jnp.take_along_axis(sums, lc[:, None], axis=1)[:, 0]
                      / jnp.maximum(own_size - 1, 1), 0.0)
        means = sums / jnp.maximum(cluster_sizes[None, :], 1)
        means = jnp.where(cluster_sizes[None, :] > 0, means, jnp.inf)
        means = means.at[jnp.arange(Xc.shape[0]), lc].set(jnp.inf)
        b = jnp.min(means, axis=1)
        s = jnp.where(own_size > 1,
                      (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
        total = total + jnp.sum(s)
    return float(total) / n


def trustworthiness_score(res, X, X_embedded, n_neighbors: int = 5,
                          metric: str = "sqeuclidean") -> float:
    """How much an embedding preserves local structure (1 = perfect).
    (ref: stats/trustworthiness_score.cuh — same definition as sklearn;
    the reference takes precomputed embedded-space knn, here both ranks are
    computed internally via pairwise distances.)"""
    X = jnp.asarray(X)
    E = jnp.asarray(X_embedded)
    n = X.shape[0]
    k = n_neighbors
    expects(k < n / 2, "trustworthiness: n_neighbors must be < n/2")
    D_orig = pairwise_distance(res, X, X, metric=metric)
    D_emb = pairwise_distance(res, E, E, metric=metric)
    big = jnp.inf
    D_orig = D_orig.at[jnp.arange(n), jnp.arange(n)].set(big)
    D_emb = D_emb.at[jnp.arange(n), jnp.arange(n)].set(big)
    # rank of j in i's original neighbor ordering (0 = nearest)
    orig_order = jnp.argsort(D_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jax.vmap(lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32)))(
        ranks, orig_order)
    # k nearest in the embedding
    _, emb_knn = jax.lax.top_k(-D_emb, k)
    r = jnp.take_along_axis(ranks, emb_knn, axis=1).astype(jnp.float32)
    penalty = jnp.sum(jnp.maximum(r - k + 1, 0.0) * (r >= k))
    norm = 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0))
    return float(1.0 - norm * penalty)


def neighborhood_recall(res, indices, ref_indices) -> float:
    """Mean |knn ∩ ref_knn| / k. (ref: stats/neighborhood_recall.cuh)"""
    a = jnp.asarray(indices)
    b = jnp.asarray(ref_indices)
    expects(a.shape == b.shape, "neighborhood_recall: shape mismatch")
    hits = (a[:, :, None] == b[:, None, :]).any(axis=2)
    return float(jnp.mean(hits.astype(jnp.float32)))
