"""Checkpointed snapshots + crash recovery for the mutation plane.

The other half of the ISSUE-12 durability plane: the WAL
(:mod:`raft_tpu.mutable.wal`) bounds what a crash can lose, this
module bounds how long recovery takes. A checkpoint is one atomic,
self-verifying copy of the full live state (rows + external ids) at an
LSN watermark:

- **slab files** are written with the shared atomic-write helper
  (:mod:`raft_tpu.core.diskio` — tmp + fsync + ``os.replace`` + parent
  directory fsync), payloads framed via ``core.serialize`` (the
  ``serialize_mdspan`` layer PAPER.md ships as ``raft::core``
  serialization, pointed at durability);
- the **manifest** carries per-file sha256, the LSN watermark, the
  snapshot generation and a schema version — a checkpoint is valid
  only if every hash verifies;
- **two-phase commit**: the ``CURRENT`` pointer file is atomically
  replaced only after the manifest is durable, so a crash at ANY
  instruction boundary leaves either the old checkpoint or the new one
  committed — never a torn pointer (fault sites ``checkpoint_write`` /
  ``manifest_commit`` + the SIGKILL matrix in tests/test_durability.py
  prove it);
- ``CheckpointStore.load`` returns the NEWEST VALID checkpoint: the
  pointer's target when it verifies, else a newest-first scan — a
  corrupt/partial checkpoint degrades to the previous one, never
  raises. WAL segments are retired only up to the OLDEST retained
  checkpoint, so the fallback always has its replay tail.

:func:`recover` is the proof-bearing entry: newest-valid-checkpoint
load + WAL tail replay through the existing ``apply_upsert`` /
``apply_delete`` — yielding a ``MutableIndex`` whose live state equals
the pre-crash index for every acked write (ids bit-identical, values
within the documented rescore rounding), with recovery wall-time /
replayed-records / truncated-bytes emitted as flight events, metrics
gauges, and the ``tools/statusz.py`` durability panel.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from raft_tpu.core.diskio import (atomic_write_bytes, atomic_write_text,
                                  fsync_dir, read_bytes)
from raft_tpu.core.serialize import mdspan_from_bytes, mdspan_to_bytes
from raft_tpu.mutable import wal as _wal
from raft_tpu.resilience import fault_point

CKPT_SCHEMA = 1
_CURRENT = "CURRENT"
_MANIFEST = "manifest.json"
#: checkpoints retained after a prune — the newest serves, the older
#: one is the fallback a torn newest degrades to
KEEP_CHECKPOINTS = 2

DURABLE_DIR_ENV = "RAFT_TPU_DURABLE_DIR"

# the durability slice of the metric vocabulary
CHECKPOINTS = "raft_tpu_checkpoints_total"
CHECKPOINT_LSN = "raft_tpu_checkpoint_lsn"
RECOVERIES = "raft_tpu_recovery_total"
RECOVERY_SECONDS = "raft_tpu_recovery_seconds"
RECOVERY_REPLAYED = "raft_tpu_recovery_replayed_records"
RECOVERY_TRUNCATED = "raft_tpu_recovery_truncated_bytes"

#: last completed recovery's stats (process-global — the statusz panel
#: reads it; None until a recovery ran)
_LAST_RECOVERY: Optional[Dict] = None


def _count(name: str, help: str, **labels) -> None:
    try:
        from raft_tpu.observability import get_registry

        get_registry().counter(name, labels or None, help=help).inc()
    except Exception:
        pass


def _gauge(name: str, value: float, help: str) -> None:
    try:
        from raft_tpu.observability import get_registry

        get_registry().gauge(name, help=help).set(value)
    except Exception:
        pass


class CheckpointData(NamedTuple):
    """One loaded-and-verified checkpoint."""

    rows: np.ndarray
    exts: np.ndarray
    lsn: int
    generation: int
    path: str


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class CheckpointStore:
    """Atomic checkpoint directory manager (see module doc)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # -- write -------------------------------------------------------------
    def write(self, rows, exts, lsn: int, generation: int) -> str:
        """Write + commit one checkpoint; returns its directory path.
        Carries ``checkpoint_write`` (before any byte lands) and
        ``manifest_commit`` (between the durable manifest and the
        pointer flip — the two-phase-commit seam the crash matrix
        kills at)."""
        fault_point("checkpoint_write")
        rows = np.ascontiguousarray(rows, np.float32)
        exts = np.ascontiguousarray(exts, np.int32)
        name = f"ckpt-{int(generation):08d}-{int(lsn):016d}"
        d = os.path.join(self.directory, name)
        os.makedirs(d, exist_ok=True)
        files = {}
        for fname, payload in (("rows.msp", mdspan_to_bytes(rows)),
                               ("exts.msp", mdspan_to_bytes(exts))):
            atomic_write_bytes(os.path.join(d, fname), payload)
            files[fname] = _sha256(payload)
        manifest = {
            "schema": CKPT_SCHEMA,
            "lsn": int(lsn),
            "generation": int(generation),
            "n_rows": int(rows.shape[0]),
            "d": int(rows.shape[1]) if rows.ndim == 2 else 0,
            "files": files,
        }
        atomic_write_text(os.path.join(d, _MANIFEST),
                          json.dumps(manifest, indent=1, sort_keys=True)
                          + "\n")
        fsync_dir(d)
        # phase two: flip the pointer — the one atomic instant the new
        # checkpoint becomes THE checkpoint
        fault_point("manifest_commit")
        atomic_write_text(os.path.join(self.directory, _CURRENT),
                          name + "\n")
        _count(CHECKPOINTS, "Mutation-plane checkpoints committed",
               status="ok")
        _gauge(CHECKPOINT_LSN, lsn,
               "LSN watermark of the newest committed checkpoint")
        try:
            from raft_tpu.observability.timeline import emit_mutation

            emit_mutation("checkpoint", lsn=int(lsn),
                          generation=int(generation),
                          rows=int(rows.shape[0]))
        except Exception:
            pass
        return d

    # -- read --------------------------------------------------------------
    def _manifest_of(self, d: str) -> Optional[Dict]:
        """Parsed-and-verified manifest of one checkpoint dir, or None
        (missing/garbage manifest, missing slab file, sha mismatch —
        every failure mode degrades to "not a checkpoint")."""
        raw = read_bytes(os.path.join(d, _MANIFEST))
        if raw is None:
            return None
        try:
            m = json.loads(raw.decode("utf-8", errors="replace"))
        except ValueError:
            return None
        if not isinstance(m, dict) or m.get("schema") != CKPT_SCHEMA:
            return None
        files = m.get("files")
        if not isinstance(files, dict) or not files:
            return None
        for fname, digest in files.items():
            payload = read_bytes(os.path.join(d, str(fname)))
            if payload is None or _sha256(payload) != digest:
                return None
        if not isinstance(m.get("lsn"), int) \
                or not isinstance(m.get("generation"), int):
            return None
        return m

    def _dirs(self) -> List[str]:
        """Checkpoint dirs, newest (generation, lsn) first."""
        return sorted(glob.glob(os.path.join(self.directory, "ckpt-*")),
                      reverse=True)

    def manifests(self) -> List[Tuple[str, Dict]]:
        """(dir, verified manifest) for every VALID checkpoint, newest
        first."""
        out = []
        for d in self._dirs():
            m = self._manifest_of(d)
            if m is not None:
                out.append((d, m))
        return out

    def load(self) -> Optional[CheckpointData]:
        """The newest VALID checkpoint: the ``CURRENT`` pointer's
        target when it verifies, else a newest-first scan; None when
        nothing durable survives. Never raises."""
        candidates: List[str] = []
        cur = read_bytes(os.path.join(self.directory, _CURRENT))
        if cur is not None:
            name = cur.decode("utf-8", errors="replace").strip()
            if name and os.sep not in name:
                candidates.append(os.path.join(self.directory, name))
        candidates.extend(d for d in self._dirs()
                          if d not in candidates)
        for d in candidates:
            m = self._manifest_of(d)
            if m is None:
                continue
            try:
                rows = mdspan_from_bytes(read_bytes(
                    os.path.join(d, "rows.msp"))).as_numpy()
                exts = mdspan_from_bytes(read_bytes(
                    os.path.join(d, "exts.msp"))).as_numpy()
            except Exception:
                continue
            return CheckpointData(rows.astype(np.float32, copy=False),
                                  exts.astype(np.int32, copy=False),
                                  int(m["lsn"]), int(m["generation"]), d)
        return None

    def prune(self, keep: int = KEEP_CHECKPOINTS) -> int:
        """Delete all but the newest ``keep`` VALID checkpoints (plus
        any invalid litter older than them); returns the retained
        checkpoints' MINIMUM lsn — the safe WAL retirement watermark
        (retiring past it would strand the fallback checkpoint without
        its replay tail)."""
        valid = self.manifests()
        keep_dirs = {d for d, _ in valid[:keep]}
        for d in self._dirs():
            if d in keep_dirs:
                continue
            try:
                shutil.rmtree(d)
            except OSError:
                pass
        retained = [m["lsn"] for d, m in valid[:keep]]
        return min(retained) if retained else 0


# ---------------------------------------------------- durability plane
class DurabilityPlane:
    """The WAL + checkpoint pair one durable ``MutableIndex`` owns.

    Layout under ``directory``: ``wal/`` (segments), ``ckpt-*/``
    (checkpoints), ``CURRENT`` (the committed pointer)."""

    def __init__(self, directory: str, sync: Optional[str] = None,
                 next_lsn: int = 1,
                 segment_bytes: Optional[int] = None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.checkpoints = CheckpointStore(directory)
        self.wal = _wal.WalWriter(os.path.join(directory, "wal"),
                                  sync=sync, next_lsn=next_lsn,
                                  segment_bytes=segment_bytes)

    # -- logging (the write-ahead half) -----------------------------------
    def log_upsert(self, ids, rows) -> int:
        return self.wal.append(_wal.OP_UPSERT,
                               _wal.encode_upsert(ids, rows))

    def log_delete(self, ids) -> int:
        return self.wal.append(_wal.OP_DELETE, _wal.encode_delete(ids))

    def commit(self) -> int:
        """The fsync horizon an ack waits on."""
        return self.wal.commit()

    # -- checkpointing ------------------------------------------------------
    def checkpoint(self, rows, exts, lsn: int, generation: int) -> str:
        """Write + commit a checkpoint at ``lsn``, mark it in the WAL,
        rotate the active segment, and retire segments the RETAINED
        checkpoints no longer need."""
        path = self.checkpoints.write(rows, exts, lsn, generation)
        self.wal.append(_wal.OP_CHECKPOINT,
                        _wal.encode_checkpoint_mark(
                            lsn, generation, os.path.basename(path)))
        self.wal.commit()
        self.wal.rotate()
        watermark = self.checkpoints.prune()
        if watermark:
            self.wal.retire_through(watermark)
        return path

    def stats(self) -> Dict:
        out = {"directory": self.directory}
        out.update(self.wal.stats())
        manifests = self.checkpoints.manifests()
        out["checkpoints"] = len(manifests)
        if manifests:
            out["checkpoint_lsn"] = manifests[0][1]["lsn"]
            out["checkpoint_generation"] = manifests[0][1]["generation"]
        return out

    def close(self) -> None:
        self.wal.close()


def has_durable_state(directory: str) -> bool:
    """True when ``directory`` holds anything recoverable (a committed
    pointer, a checkpoint dir, or WAL segments)."""
    if not directory or not os.path.isdir(directory):
        return False
    if os.path.exists(os.path.join(directory, _CURRENT)):
        return True
    if glob.glob(os.path.join(directory, "ckpt-*")):
        return True
    return bool(glob.glob(os.path.join(directory, "wal", "wal-*.log")))


def last_recovery() -> Optional[Dict]:
    """The process's most recent recovery stats (statusz panel)."""
    return dict(_LAST_RECOVERY) if _LAST_RECOVERY else None


def recover(directory: str, *, res=None, wal_sync: Optional[str] = None,
            attach: bool = True, **mutable_kw):
    """Rebuild a ``MutableIndex`` from the newest valid checkpoint +
    the WAL tail (see module doc). Returns ``(index, stats)`` or None
    when nothing durable survives (an empty/virgin directory — by the
    genesis-checkpoint invariant nothing was ever acked from it).

    ``attach=True`` re-attaches a live durability plane (appends
    continue past the recovered tail) and, when any records were
    replayed, writes a fresh checkpoint so the NEXT recovery starts
    from a rebounded tail. ``attach=False`` is the inspection mode the
    crash-matrix verifier uses. ``mutable_kw`` forwards the index
    geometry (algorithm / passes / T / Qb / g / db_dtype / ...)."""
    global _LAST_RECOVERY

    from raft_tpu.mutable.index import (MutableIndex, apply_delete,
                                        apply_upsert)

    t0 = time.perf_counter()
    store = CheckpointStore(directory)
    ck = store.load()
    if ck is None:
        _count(RECOVERIES, "Mutation-plane recoveries by outcome",
               status="empty")
        return None
    idx = MutableIndex(ck.rows, ids=ck.exts, res=res, **mutable_kw)
    records, rstats = _wal.replay(os.path.join(directory, "wal"),
                                  from_lsn=ck.lsn, truncate=True)
    replayed = 0
    for rec in records:
        try:
            if rec.op == _wal.OP_UPSERT:
                ids, rows = _wal.decode_upsert(rec.payload)
                apply_upsert(idx, ids, rows)
            elif rec.op == _wal.OP_DELETE:
                apply_delete(idx, _wal.decode_delete(rec.payload))
            replayed += 1
        except Exception as e:
            # a record that decodes/applies no further marks the end
            # of the consistent prefix — same contract as a torn tail
            from raft_tpu.core.logger import log_warn

            log_warn("recovery: WAL replay stopped at lsn %d (%s: %s) "
                     "— recovered through the preceding record",
                     rec.lsn, type(e).__name__, str(e)[:200])
            rstats["stopped_early"] = True
            rstats["stop_reason"] = f"replay: {type(e).__name__}"
            break
    seconds = time.perf_counter() - t0
    stats = {
        "checkpoint_lsn": ck.lsn,
        "checkpoint_generation": ck.generation,
        "checkpoint_path": os.path.basename(ck.path),
        "checkpoint_rows": int(ck.rows.shape[0]),
        "replayed_records": replayed,
        "truncated_bytes": int(rstats.get("truncated_bytes", 0)),
        "wal_last_lsn": int(rstats.get("last_lsn", 0)),
        "stopped_early": bool(rstats.get("stopped_early")),
        "stop_reason": rstats.get("stop_reason", ""),
        "seconds": seconds,
    }
    if attach:
        next_lsn = max(ck.lsn, stats["wal_last_lsn"]) + 1
        idx._attach_durability(
            DurabilityPlane(directory, sync=wal_sync,
                            next_lsn=next_lsn))
        if replayed:
            # rebound the tail: the next recovery replays from here
            idx.checkpoint()
    _count(RECOVERIES, "Mutation-plane recoveries by outcome",
           status="ok")
    _gauge(RECOVERY_SECONDS, seconds,
           "Wall time of the last crash recovery")
    _gauge(RECOVERY_REPLAYED, replayed,
           "WAL records replayed by the last recovery")
    _gauge(RECOVERY_TRUNCATED, stats["truncated_bytes"],
           "Torn-tail bytes truncated by the last recovery")
    try:
        from raft_tpu.observability.timeline import emit_mutation

        emit_mutation("recovery", **{k: v for k, v in stats.items()
                                     if k != "stop_reason"})
    except Exception:
        pass
    _LAST_RECOVERY = dict(stats)
    return idx, stats
