"""IndexLayout — the one slab description every index plane shares.

Until this PR each plane carried its own private spelling of "a slab of
rows, some of them real": ``prepare_knn_index`` kept a trailing-pad
prefix count, the IVF builder kept (offsets, sizes, padded_sizes, ids)
around its padded ragged slab, and the quantized planes bolted their
scale/Eq sidecars onto whichever of the two they rode. The mutable
subsystem (:mod:`raft_tpu.mutable.index`) needs all three shapes to be
the SAME thing — a base snapshot, a delta tail and a tombstoned slab
are all just layouts with different ``rows_valid`` masks — so the
struct is extracted here and the build/search machinery re-expressed
as pure ops over it:

- :class:`IndexLayout` — slab (f32 rows, pads zero), ids (slab row →
  global id, −1 pad), ``rows_valid`` (the live mask — pads AND
  tombstones), optional IVF geometry (offsets/sizes/padded_sizes) and
  optional per-row int8 sidecar (codes, scale, Eq).
- :func:`dense_layout` — a flat matrix as a layout (the brute plane /
  the mutable delta slab).
- :func:`ragged_layout_from_lists` — the padded-ragged-slab
  construction extracted from ``ann.build_ivf_flat`` (host-side
  bucketing by label, each list padded to the row quantum).
- :func:`quantize_layout` — the per-list int8 sidecar (PR-9
  ``quantize_rows_q8`` / Eq machinery) over a ragged layout.
- :func:`fused_ops_for_layout` / :func:`run_fused_ops` — prepared
  certified-fused operands over ANY layout (the ragged ``rows_valid``
  sentinel path) and the chunked core driver over them. ``ann.
  _slab_fused_geometry`` (the IVF degenerate-exact plane) and the
  mutable base/delta planes all call these two — one spelling of the
  geometry, no drifting copies.

Everything here is functional: a layout never mutates. The mutable
index expresses a tombstone as a NEW ``rows_valid`` (plus the matching
never-wins sentinel scatter on the prepared carrier) — the slab is
untouched, which is what makes deletes O(changed) instead of O(index).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import numpy as np

#: slab row quantum every layout pads its row groups to — the fused
#: pipeline's 8-row sublane multiple (mirrors ann.DEFAULT_ROW_QUANTUM)
ROW_QUANTUM = 8


class IndexLayout:
    """One slab of index rows + the masks/sidecars every plane needs.

    ``slab`` [R, d] f32 (pad rows zero), ``ids`` [R] int32 (slab row →
    global row id, −1 on pads), ``rows_valid`` [R] bool (live rows —
    False on pads AND tombstones). ``offsets``/``sizes``/
    ``padded_sizes`` carry the IVF inverted-list geometry when the
    layout is ragged-by-list (None for flat layouts). The int8 sidecar
    (``slab_q``/``row_scale``/``eq_rows``) is per-ROW — the IVF shape;
    the brute plane's per-certificate-group quantization re-derives
    from the f32 slab in ``_prepare_ops_q8``."""

    __slots__ = ("slab", "ids", "rows_valid", "offsets", "sizes",
                 "padded_sizes", "row_quantum", "d_orig", "n_rows",
                 "db_dtype", "slab_q", "row_scale", "eq_rows",
                 "pq_codes", "pq_yy", "pq_eq_rows", "pq_rot",
                 "pq_meta")

    def __init__(self, slab, ids, rows_valid, n_rows: int, d_orig: int,
                 offsets=None, sizes=None, padded_sizes=None,
                 row_quantum: int = ROW_QUANTUM, db_dtype: str = "f32",
                 slab_q=None, row_scale=None, eq_rows=None,
                 pq_codes=None, pq_yy=None, pq_eq_rows=None,
                 pq_rot=None, pq_meta=None):
        self.slab = slab
        self.ids = ids
        self.rows_valid = rows_valid
        self.n_rows = int(n_rows)
        self.d_orig = int(d_orig)
        self.offsets = offsets
        self.sizes = sizes
        self.padded_sizes = padded_sizes
        self.row_quantum = int(row_quantum)
        self.db_dtype = db_dtype
        self.slab_q = slab_q
        self.row_scale = row_scale
        self.eq_rows = eq_rows
        # product-quantized sidecar (ann.ivf_pq — the compressed tier):
        # the packed codes slab + reconstructed norms ride the SAME
        # padded-ragged row geometry as the f32 slab, so tombstones /
        # compaction treat them as one more per-row column
        self.pq_codes = pq_codes
        self.pq_yy = pq_yy
        self.pq_eq_rows = pq_eq_rows
        # the OPQ learned rotation ([d, d] orthogonal, None for plain
        # PQ) — per-INDEX, not per-row: compaction and tombstone folds
        # carry it through unchanged
        self.pq_rot = pq_rot
        self.pq_meta = pq_meta

    @property
    def slab_rows(self) -> int:
        return int(self.slab.shape[0])

    @property
    def ragged(self) -> bool:
        return self.offsets is not None

    def __repr__(self):
        return (f"IndexLayout(rows={self.n_rows}, slab={self.slab_rows}, "
                f"d={self.d_orig}, ragged={self.ragged}, "
                f"db_dtype={self.db_dtype})")


def dense_layout(y, ids=None, rows_valid=None,
                 row_quantum: int = ROW_QUANTUM) -> IndexLayout:
    """A flat [m, d] matrix as an :class:`IndexLayout`: rows pad up to
    the row quantum (pad rows zero, ids −1, invalid). ``ids`` defaults
    to ``arange(m)``; ``rows_valid`` (over the INPUT rows) marks
    tombstoned/garbage rows out — the mutable delta slab passes its
    occupancy mask here. Host-side (numpy in, numpy out) — the device
    transfer happens once, in :func:`fused_ops_for_layout`."""
    y = np.asarray(y, np.float32)
    m, d = y.shape
    R = max(row_quantum, -(-m // row_quantum) * row_quantum)
    slab = np.zeros((R, d), np.float32)
    slab[:m] = y
    out_ids = np.full(R, -1, np.int32)
    out_ids[:m] = (np.arange(m, dtype=np.int32) if ids is None
                   else np.asarray(ids, np.int32))
    valid = np.zeros(R, np.bool_)
    valid[:m] = True if rows_valid is None else \
        np.asarray(rows_valid, np.bool_).reshape(-1)
    valid &= out_ids >= 0
    return IndexLayout(slab, out_ids, valid, n_rows=m, d_orig=d,
                       row_quantum=row_quantum)


def ragged_layout_from_lists(y, labels, n_lists: int,
                             row_quantum: int = ROW_QUANTUM
                             ) -> IndexLayout:
    """The padded ragged slab: rows of ``y`` bucketed by ``labels``
    into ``n_lists`` inverted lists, each list padded up to the row
    quantum, lists back-to-back in one [R, d] slab with offsets/sizes/
    global ids alongside — the host-side layout block extracted from
    ``ann.build_ivf_flat`` (memory is Σ padded, not L·max; empty lists
    cost 0 rows). Host-side numpy throughout."""
    y = np.asarray(y, np.float32)
    labels = np.asarray(labels)
    m, d = y.shape
    L = int(n_lists)
    sizes = np.bincount(labels, minlength=L).astype(np.int32)
    padded = ((sizes + row_quantum - 1) // row_quantum
              * row_quantum).astype(np.int32)
    padded[sizes == 0] = 0                     # empty lists cost nothing
    offsets = np.concatenate(
        [[0], np.cumsum(padded, dtype=np.int64)]).astype(np.int32)
    R = int(offsets[-1])
    slab = np.zeros((R, d), np.float32)
    ids = np.full(R, -1, np.int32)
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    # rank of each row within its list (order is label-sorted, so the
    # rank is position minus the first position of that label)
    first = np.concatenate([[0], np.cumsum(sizes, dtype=np.int64)[:-1]])
    rank = np.arange(m) - first[sorted_labels]
    dest = offsets[sorted_labels] + rank
    slab[dest] = y[order]
    ids[dest] = order.astype(np.int32)
    return IndexLayout(slab, ids, ids >= 0, n_rows=m, d_orig=d,
                       offsets=offsets, sizes=sizes, padded_sizes=padded,
                       row_quantum=row_quantum)


def quantize_layout(layout: IndexLayout) -> IndexLayout:
    """Per-list symmetric int8 sidecar over a RAGGED layout (the PR-9
    machinery: ``quantize_rows_q8`` grouped by inverted list, per-row
    scale/Eq gathered alongside the codes — the cuVS int8 IVF-Flat
    shape). Returns a new layout; the f32 slab stays (it is the exact-
    rescore data plane)."""
    import jax.numpy as jnp

    from raft_tpu.distance.knn_fused import q8_eq_bound, quantize_rows_q8

    if not layout.ragged:
        raise ValueError("quantize_layout: per-list quantization needs "
                         "a ragged (IVF) layout — the brute plane "
                         "quantizes per certificate group in "
                         "_prepare_ops_q8")
    L = int(layout.sizes.shape[0])
    gid = jnp.asarray(np.repeat(np.arange(L, dtype=np.int32),
                                np.asarray(layout.padded_sizes)))
    slab_j = jnp.asarray(layout.slab)
    valid = jnp.asarray(np.asarray(layout.ids) >= 0)
    slab_q, list_scale = quantize_rows_q8(slab_j, gid, L, valid=valid)
    eq_lists = q8_eq_bound(list_scale, layout.slab.shape[1])
    row_scale = jnp.take(list_scale, gid)
    return IndexLayout(layout.slab, layout.ids, layout.rows_valid,
                       n_rows=layout.n_rows, d_orig=layout.d_orig,
                       offsets=layout.offsets, sizes=layout.sizes,
                       padded_sizes=layout.padded_sizes,
                       row_quantum=layout.row_quantum, db_dtype="int8",
                       slab_q=slab_q, row_scale=row_scale,
                       eq_rows=jnp.take(eq_lists, gid))


class FusedOps(NamedTuple):
    """Prepared certified-fused operands over one layout: everything
    :func:`run_fused_ops` needs to drive ``_knn_fused_core`` with the
    ragged ``rows_valid`` sentinel path. ``ops`` is the positional
    operand tuple (f32: yp/y_hi/y_lo/yyh_k/yy_raw; int8:
    yp/y_q/scale_k/yyh_k/yy_raw/eq_groups); ``rv`` is the PREPARED
    (row-padded) live mask; ``ids`` maps slab positions back to the
    layout's global ids (−1 pads), padded to the prepared row count."""

    db_dtype: str
    ops: Tuple
    rv: object
    ids: object
    T: int
    Qb: int
    g: int
    pbits: int
    grid_order: str
    passes: int
    metric: str

    @property
    def slab_rows(self) -> int:
        """PREPARED (padded) row count."""
        return int(self.ops[0].shape[0])

    @property
    def yyh_index(self) -> int:
        """Position of the sentinel carrier in ``ops`` — the one
        operand a tombstone scatter replaces."""
        return 3 if self.db_dtype == "int8" else 3

    @property
    def pool_width(self) -> int:
        n_tiles = self.slab_rows // self.T
        return 2 * (-(-n_tiles // self.g)) * 128


def fused_geometry(slab_rows: int, d: int, passes: int = 3,
                   T: Optional[int] = None, Qb: Optional[int] = None,
                   g: Optional[int] = None
                   ) -> Tuple[int, int, int, int]:
    """(T, Qb, g, pbits) for a certified-fused program over a slab of
    ``slab_rows`` × ``d`` — the ONE spelling of the packed ragged
    geometry (tuned config → scoped-VMEM fit → auto pack width →
    packed-envelope clamp), shared by the IVF degenerate-exact plane
    and the mutable base/delta planes. The ragged ``rows_valid`` mask
    is packed-only, so ``g`` is clamped into the code space."""
    from raft_tpu.distance.knn_fused import (_LANES, _PACK_BITS,
                                             _PBITS_MAX, auto_pack_bits,
                                             fit_config, fused_config)

    cfg = fused_config(passes)
    T = cfg.T if T is None else T
    Qb = cfg.Qb if Qb is None else Qb
    T, Qb = fit_config(T, Qb, d, passes, g or cfg.g, "query")
    n_tiles_est = max(1, -(-slab_rows // T))
    if g is None:
        g = max(cfg.g,
                (1 << auto_pack_bits(n_tiles_est, T)) // (T // _LANES))
    n_ch = T // _LANES
    pbits = min(_PBITS_MAX, max(_PACK_BITS, int(math.ceil(math.log2(
        max(g * n_ch, 2))))))
    if g * n_ch > (1 << pbits):
        g = max(1, (1 << pbits) // n_ch)   # ragged mask is packed-only
    return T, Qb, g, pbits


def fused_ops_for_layout(layout: IndexLayout, passes: int = 3,
                         metric: str = "l2",
                         T: Optional[int] = None,
                         Qb: Optional[int] = None,
                         g: Optional[int] = None,
                         db_dtype: Optional[str] = None) -> FusedOps:
    """Prepare the certified-fused operands for ``layout`` — the pure
    build op every plane shares: d-pad the slab, resolve the packed
    geometry (:func:`fused_geometry`), run ``_prepare_ops`` (or the
    int8 ``_prepare_ops_q8``) with the layout's ``rows_valid`` as the
    ragged never-wins mask, and return the operand bundle + the padded
    id map. ``db_dtype`` "int8" streams the slab quantized per
    certificate group (database-major, mandatory exact f32 rescore —
    the PR-9 contract); default follows the layout (ragged int8
    sidecars still rescore from the f32 slab here)."""
    import jax.numpy as jnp

    from raft_tpu.distance.knn_fused import (_LANES, _prepare_ops,
                                             _prepare_ops_q8)

    slab = jnp.asarray(layout.slab, jnp.float32)
    R, d = slab.shape
    T, Qb, g, pbits = fused_geometry(R, d, passes, T=T, Qb=Qb, g=g)
    dpad = (-d) % _LANES
    if dpad:
        slab = jnp.concatenate(
            [slab, jnp.zeros((R, dpad), jnp.float32)], axis=1)
    valid = jnp.asarray(np.asarray(layout.rows_valid), jnp.bool_)
    quant = (db_dtype or "f32") == "int8"
    grid_order = "db" if quant else "query"
    if quant:
        ops = _prepare_ops_q8(slab, T, g, metric, pbits=pbits,
                              grid_order=grid_order, rows_valid=valid)
    else:
        ops = _prepare_ops(slab, T, g, metric, pbits=pbits,
                           grid_order=grid_order, rows_valid=valid)
    M = ops[0].shape[0]
    ids = jnp.asarray(np.asarray(layout.ids), jnp.int32)
    rv = valid
    if M > R:
        pad = M - R
        ids = jnp.concatenate([ids, jnp.full((pad,), -1, jnp.int32)])
        rv = jnp.concatenate([rv, jnp.zeros((pad,), jnp.bool_)])
    try:
        from raft_tpu.observability.timeline import emit_marker

        emit_marker("layout_fused_ops", slab_rows=int(M), d=int(d),
                    T=T, Qb=Qb, g=g, pbits=pbits,
                    db_dtype="int8" if quant else "f32",
                    ragged=layout.ragged)
    except Exception:
        pass
    return FusedOps(db_dtype="int8" if quant else "f32", ops=tuple(ops),
                    rv=rv, ids=ids, T=T, Qb=Qb, g=g, pbits=pbits,
                    grid_order=grid_order, passes=passes, metric=metric)


def run_fused_ops(fops: FusedOps, x, k: int, rows_valid=None,
                  yyh_k=None) -> Tuple:
    """Drive ``_knn_fused_core`` over prepared layout operands — the
    pure search op. Handles query d-padding, Qb row padding and the
    ``_Q_CHUNK`` workspace bound exactly like ``knn_fused``'s wrapper.

    ``rows_valid``/``yyh_k`` override the prepared mask/carrier: the
    mutable planes pass their tombstone-updated pair (same shapes →
    the jit cache serves every mutation generation from ONE compiled
    program). Returns ``(vals [nq, k], pos [nq, k] slab positions,
    n_fail device scalar)`` — callers map positions through
    ``fops.ids`` and report ``n_fail`` to the quality plane."""
    import jax.numpy as jnp

    from raft_tpu.distance.knn_fused import _Q_CHUNK, _knn_fused_core

    x = jnp.asarray(x, jnp.float32)
    nq = x.shape[0]
    rv = fops.rv if rows_valid is None else rows_valid
    ops = list(fops.ops)
    if yyh_k is not None:
        ops[fops.yyh_index] = yyh_k
    if nq == 0:
        z = jnp.zeros((0, k), jnp.float32)
        return z, jnp.zeros((0, k), jnp.int32), jnp.int32(0)
    if nq > _Q_CHUNK:
        outs = [run_fused_ops(fops, x[s:s + _Q_CHUNK], k,
                              rows_valid=rows_valid, yyh_k=yyh_k)
                for s in range(0, nq, _Q_CHUNK)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]),
                sum(o[2] for o in outs))
    M = fops.slab_rows
    if k > fops.pool_width:
        raise NotImplementedError(
            f"run_fused_ops: k={k} too large for the layout's candidate "
            f"pool {fops.pool_width} (shrink k or grow the slab)")
    dpad = ops[0].shape[1] - x.shape[1]
    if dpad:
        x = jnp.concatenate(
            [x, jnp.zeros((nq, dpad), jnp.float32)], axis=1)
    Qb_eff = min(fops.Qb, ((nq + 7) // 8) * 8)
    qpad = (-nq) % Qb_eff
    if qpad:
        x = jnp.concatenate(
            [x, jnp.zeros((qpad, x.shape[1]), jnp.float32)])
    common = dict(k=k, T=fops.T, Qb=Qb_eff, g=fops.g, passes=fops.passes,
                  metric=fops.metric, m=M, rescore=True,
                  pbits=fops.pbits, with_stats=True, rows_valid=rv,
                  grid_order=fops.grid_order)
    # margin (4th with_stats output) is discarded inside this jitted
    # view — the mutable plane's explain story rides the base-search
    # sites; XLA DCEs the unused output
    if fops.db_dtype == "int8":
        yp, y_q, scale_k, yyh, yy_raw, eq = ops
        vals, pos, n_fail, _ = _knn_fused_core(
            x, yp, None, None, yyh, yy_raw, db_dtype="int8", y_q=y_q,
            y_scale_k=scale_k, eq_groups=eq, **common)
    else:
        yp, y_hi, y_lo, yyh, yy_raw = ops
        vals, pos, n_fail, _ = _knn_fused_core(
            x, yp, y_hi, y_lo, yyh, yy_raw, **common)
    vals, pos = vals[:nq], pos[:nq]
    # rows short of k come back (+inf, <raw column>) from the fixup's
    # unmasked top_k — an id consumers would happily map to a TOMBSTONED
    # row; normalize every non-finite slot to the −1 sentinel here
    pos = jnp.where(jnp.isfinite(vals), pos, -1)
    return vals, pos, n_fail
