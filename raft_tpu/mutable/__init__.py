"""raft_tpu.mutable — streaming upserts/deletes over immutable bases.

The mutation plane (ROADMAP item 3): every index gets an append delta
slab (8-row quantum, quantized/certified on ingest), tombstone bitmaps
applied through the ragged never-wins sentinel path (a delete is
visible on the next batch without touching the slab), a two-slab
search merged with the PR-4 rank-ordered merge, and a background
compactor that folds deltas past ``RAFT_TPU_COMPACT_THRESHOLD`` into a
fresh snapshot through the existing warmed rebuild-and-swap — readers
never block, generation semantics stay last-wins.

- :class:`~raft_tpu.mutable.index.MutableIndex` — the mutation plane
  (brute f32 / brute int8 / IVF-Flat bases).
- :mod:`~raft_tpu.mutable.layout` — :class:`IndexLayout`, the explicit
  slab struct (slab, ids, offsets/sizes, rows_valid, int8 sidecar)
  shared by the brute, IVF-Flat and quantized planes, with the
  build/search machinery re-expressed as pure ops over it.

Durability (ISSUE 12): :mod:`~raft_tpu.mutable.wal` (segmented
write-ahead log — framed records, CRC trailers, group-commit fsync,
torn-tail truncation) + :mod:`~raft_tpu.mutable.checkpoint` (atomic
manifest-verified checkpoints, two-phase ``CURRENT`` commit, and
:func:`~raft_tpu.mutable.checkpoint.recover` = newest-valid-checkpoint
load + WAL tail replay). ``MutableIndex(durable_dir=...)`` /
``ServingEngine(durable=True)`` turn it on; acked writes then survive
SIGKILL at any instruction boundary (the crash matrix in
tests/test_durability.py).

Evidence: ``benchmarks/bench_mutation.py`` drives a closed-loop mixed
read/write load across a full compaction cycle and writes
``BENCH_MUTATION.json``; ``benchmarks/bench_recovery.py`` measures the
durable-write overhead + recovery time vs WAL tail length and writes
``BENCH_RECOVERY.json`` — both gated by ``tools/bench_report.py
--check``.
"""

from raft_tpu.mutable.checkpoint import (CheckpointStore,
                                         DurabilityPlane,
                                         has_durable_state,
                                         last_recovery, recover)
from raft_tpu.mutable.index import (COMPACT_THRESHOLD_ENV,
                                    DELTA_CAP_ENV, MutableIndex,
                                    MutableView, apply_delete,
                                    apply_upsert,
                                    compact_threshold_default,
                                    delta_cap_default, search_view)
from raft_tpu.mutable.wal import (OP_CHECKPOINT, OP_DELETE, OP_UPSERT,
                                  WalRecord, WalWriter,
                                  replay as wal_replay)
from raft_tpu.mutable.layout import (FusedOps, IndexLayout, dense_layout,
                                     fused_geometry, fused_ops_for_layout,
                                     quantize_layout,
                                     ragged_layout_from_lists,
                                     run_fused_ops)

__all__ = [
    "COMPACT_THRESHOLD_ENV",
    "CheckpointStore",
    "DELTA_CAP_ENV",
    "DurabilityPlane",
    "FusedOps",
    "IndexLayout",
    "MutableIndex",
    "MutableView",
    "OP_CHECKPOINT",
    "OP_DELETE",
    "OP_UPSERT",
    "WalRecord",
    "WalWriter",
    "apply_delete",
    "apply_upsert",
    "compact_threshold_default",
    "delta_cap_default",
    "dense_layout",
    "fused_geometry",
    "fused_ops_for_layout",
    "has_durable_state",
    "last_recovery",
    "quantize_layout",
    "ragged_layout_from_lists",
    "recover",
    "run_fused_ops",
    "search_view",
    "wal_replay",
]
