"""MutableIndex — streaming upserts/deletes over immutable snapshots.

Every index in this repo was frozen at build: the only update path was
``SnapshotStore``'s full rebuild-and-swap (PR 7) — O(index) per change.
This module gives every plane a MUTATION plane (ROADMAP item 3, the
raft-dask rebuild/redistribute orchestration re-imagined as a serving
feature):

- **base snapshot** — an immutable prepared index (brute f32, brute
  int8, or IVF-Flat) held in a :class:`~raft_tpu.serving.snapshot.
  SnapshotStore`; readers take a consistent :class:`MutableView` and
  NEVER block on a writer.
- **append delta slab** — a fixed-capacity [cap, d] tail sized to the
  8-row quantum. New rows land in the next free slots and the delta is
  re-prepared through the SAME certified fused machinery as the base
  (:func:`raft_tpu.mutable.layout.fused_ops_for_layout` — int8 bases
  quantize/certify delta rows on ingest via the PR-9
  ``quantize_rows_q8``/Eq path, so the delta tail streams through the
  same certified kernels). Fixed capacity means fixed shapes: every
  mutation generation serves from the same compiled programs.
- **tombstones** — a delete (or the old copy under an upsert) flips the
  row's ``rows_valid`` bit and scatters the never-wins sentinel into
  the prepared carrier column (the ragged PR-8 path): O(changed) work,
  the slab itself untouched, and the delete is visible to the very next
  batch. IVF bases additionally mask the row's slab id so the probed
  fine scan skips it.
- **two-slab search** — a query runs the base plane (tombstone-masked)
  and the delta plane and merges the two top-k pools with the PR-4
  rank-major merge (:func:`raft_tpu.distance.knn_sharded.
  _merge_host_pool`) — deterministic, exact-value preserving, so
  interleaved mutations stay id-identical to a from-scratch rebuild
  oracle (pinned by tests/test_mutable.py on all three planes).
- **background compaction** — past ``RAFT_TPU_COMPACT_THRESHOLD``
  delta slots, a compactor thread folds (live base + live delta) into a
  fresh snapshot through the EXISTING warmed rebuild-and-swap
  (``SnapshotStore.update``), then rebases the retained delta tail and
  any tombstones that landed mid-fold onto the new base. Readers keep
  the old view until the swap; generation semantics stay last-wins; a
  crash anywhere in the fold keeps the old snapshot serving (no torn
  generation — the ``compact_fold`` fault site + tests pin it).
- **write-ahead flight events** — every mutation emits through
  :func:`~raft_tpu.observability.timeline.emit_mutation`
  (upsert/delete/compact_start/compact_swap/compact_abort) next to live
  gauges: delta occupancy, tombstone fraction, compaction debt.

Env knobs (README "Mutable indexes & compaction"):

- ``RAFT_TPU_COMPACT_THRESHOLD`` — delta slots that trigger a
  background fold (default 1024).
- ``RAFT_TPU_DELTA_CAP`` — delta slab capacity (default 2× the
  threshold, rounded to the 8-row quantum). A writer that fills the
  cap while a fold is in flight WAITS for the swap — writers may
  block, readers never.

Durability (ISSUE 12, default OFF): ``durable_dir=`` attaches a
:class:`~raft_tpu.mutable.checkpoint.DurabilityPlane` — every mutation
is appended to the segmented WAL BEFORE it is applied and fsynced (per
``wal_sync`` / ``RAFT_TPU_WAL_SYNC``) BEFORE it returns, so an acked
write survives a crash; the compactor commits an atomic checkpoint at
every swap (and a genesis checkpoint at attach, so recovery always has
a floor); :func:`raft_tpu.mutable.checkpoint.recover` rebuilds the
index from newest-valid-checkpoint + WAL tail replay. With
``durable_dir=None`` the plane is ``None`` and the mutation/search hot
paths are byte-for-byte the PR-11 ones — no new dispatches, no
compile-cache traffic (pinned by tests/test_durability.py).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core import env
from raft_tpu.core.error import expects
from raft_tpu.observability import instrument
from raft_tpu.observability.quality import record_pending
from raft_tpu.observability.timeline import emit_mutation
from raft_tpu.resilience import fault_point

from raft_tpu.mutable.layout import (FusedOps, IndexLayout, dense_layout,
                                     fused_ops_for_layout, run_fused_ops)

COMPACT_THRESHOLD_ENV = "RAFT_TPU_COMPACT_THRESHOLD"
DELTA_CAP_ENV = "RAFT_TPU_DELTA_CAP"
DEFAULT_COMPACT_THRESHOLD = 1024

# the mutation slice of the metric vocabulary
DELTA_ROWS = "raft_tpu_mutable_delta_rows"
TOMBSTONE_FRAC = "raft_tpu_mutable_tombstone_frac"
COMPACTION_DEBT = "raft_tpu_mutable_compaction_debt"
MUTATIONS = "raft_tpu_mutable_mutations_total"
COMPACTIONS = "raft_tpu_mutable_compactions_total"

#: delta-plane tiling: small fixed geometry — the delta slab is bounded
#: by the compact threshold, so the tuned production tile would mostly
#: pad (T must stay a multiple of 128, Qb of 8)
_DELTA_T = 256
_DELTA_QB = 128
_DELTA_G = 2


def compact_threshold_default() -> int:
    return max(8, env.get(COMPACT_THRESHOLD_ENV,
                          DEFAULT_COMPACT_THRESHOLD))


def delta_cap_default(threshold: int) -> int:
    cap = env.get(DELTA_CAP_ENV)
    if cap is None:
        cap = 2 * threshold
    cap = max(cap, threshold, 8)
    return -(-cap // 8) * 8                       # 8-row quantum


def _gauges(registry, delta_rows: int, cap: int, tombs: int,
            base_rows: int, threshold: int) -> None:
    try:
        registry.gauge(
            DELTA_ROWS, help="Delta-slab slots written (live + "
                             "tombstoned) awaiting compaction"
        ).set(delta_rows)
        registry.gauge(
            TOMBSTONE_FRAC,
            help="Tombstoned fraction of the base snapshot's rows"
        ).set(tombs / max(1, base_rows))
        registry.gauge(
            COMPACTION_DEBT,
            help="Delta occupancy over the compaction watermark "
                 "(>= 1.0 means a fold is due)"
        ).set(delta_rows / max(1, threshold))
    except Exception:
        pass


class _BasePlane:
    """One immutable base snapshot: the prepared index + its external-id
    maps + the certified-fused operand bundle the mutable search drives.
    Never mutated — tombstone state lives in :class:`MutableIndex` and
    is rebuilt per swap."""

    __slots__ = ("kind", "index", "exts_np", "fops", "ext_slab",
                 "ext_row", "n_rows", "d_orig", "Qb")

    def __init__(self, kind: str, index, exts_np: np.ndarray,
                 fops: FusedOps):
        import jax.numpy as jnp

        self.kind = kind
        self.index = index
        self.exts_np = np.asarray(exts_np, np.int32)
        self.fops = fops
        self.n_rows = int(index.n_rows)
        self.d_orig = int(index.d_orig)
        self.Qb = int(index.Qb)
        M = fops.slab_rows
        # slab position → external id (pads −1): brute slab positions
        # ARE row ids; IVF slab positions map through the layout ids
        if kind == "brute":
            ext_slab = np.full(M, -1, np.int32)
            ext_slab[:self.n_rows] = self.exts_np
        else:
            ids = np.asarray(fops.ids)
            ext_slab = np.where(ids >= 0, self.exts_np[np.maximum(ids, 0)],
                                -1).astype(np.int32)
        self.ext_slab = jnp.asarray(ext_slab)
        # global row id → external id (the IVF probe path returns row
        # ids; the brute plane uses ext_slab directly)
        self.ext_row = jnp.asarray(self.exts_np)


def _brute_fops(idx) -> FusedOps:
    """The FusedOps bundle of an already-prepared dense
    :class:`~raft_tpu.distance.knn_fused.KnnIndex` — the brute base
    plane reuses the snapshot's operands verbatim (no re-prep); only
    the mask/carrier pair is overridden per mutation generation."""
    import jax.numpy as jnp

    M = idx.yyh_k.shape[1]
    rv = jnp.arange(M, dtype=jnp.int32) < idx.n_rows
    ids = jnp.where(rv, jnp.arange(M, dtype=jnp.int32), -1)
    if idx.db_dtype == "int8":
        ops = (idx.yp, idx.y_q, idx.y_scale_k, idx.yyh_k, idx.yy_raw,
               idx.eq_groups)
    else:
        ops = (idx.yp, idx.y_hi, idx.y_lo, idx.yyh_k, idx.yy_raw)
    return FusedOps(db_dtype="int8" if idx.db_dtype == "int8" else "f32",
                    ops=ops, rv=rv, ids=ids, T=idx.T, Qb=idx.Qb,
                    g=idx.g, pbits=idx.pbits, grid_order=idx.grid_order,
                    passes=idx.passes, metric=idx.metric)


class MutableView:
    """One consistent read view: immutable references to the base
    plane, its tombstone-updated (mask, carrier) pair, the prepared
    delta operands and the live counts — everything a search needs,
    captured under the writer lock in O(1). Queries racing a mutation
    or a compaction swap each see exactly one generation."""

    __slots__ = ("plane", "base_rv", "base_yyh", "ids_live", "base_live",
                 "delta_fops", "delta_live", "generation", "seq")

    def __init__(self, plane, base_rv, base_yyh, ids_live, base_live,
                 delta_fops, delta_live, generation, seq):
        self.plane = plane
        self.base_rv = base_rv
        self.base_yyh = base_yyh
        self.ids_live = ids_live
        self.base_live = base_live
        self.delta_fops = delta_fops
        self.delta_live = delta_live
        self.generation = generation
        self.seq = seq

    @property
    def n_rows(self) -> int:
        """Live logical row count (base + delta, tombstones excluded)."""
        return self.base_live + self.delta_live


class MutableIndex:
    """A mutation plane over any supported index (see the module doc).

    ``index`` may be a raw [m, d] matrix, a prepared ``KnnIndex``
    (``algorithm="brute"`` — requires ``store_yp``; the f32 rows are
    the compaction source), or an ``IvfFlatIndex``
    (``algorithm="ivf_flat"``, f32 slab). ``ids`` are the EXTERNAL
    row ids (non-negative int32; default ``arange(m)``) — searches
    return them, upserts/deletes address them.
    """

    def __init__(self, index, ids=None, *, algorithm: str = "brute",
                 res=None, passes: int = 3, metric: str = "l2",
                 T: Optional[int] = None, Qb: Optional[int] = None,
                 g: Optional[int] = None, db_dtype: Optional[str] = None,
                 n_lists: Optional[int] = None,
                 n_probes: Optional[int] = None,
                 pq_dim: Optional[int] = None,
                 pq_bits: Optional[int] = None,
                 compact_threshold: Optional[int] = None,
                 delta_cap: Optional[int] = None,
                 auto_compact: bool = True,
                 durable_dir: Optional[str] = None,
                 wal_sync: Optional[str] = None):
        from raft_tpu.ann import IvfFlatIndex, IvfPqIndex
        from raft_tpu.core.resources import ensure_resources
        from raft_tpu.distance.knn_fused import KnnIndex

        expects(algorithm in ("brute", "ivf_flat", "ivf_pq"),
                "MutableIndex: algorithm must be 'brute', 'ivf_flat' "
                "or 'ivf_pq', got %r", algorithm)
        expects(metric == "l2",
                "MutableIndex: the mutation plane serves metric='l2' "
                "only (the merge and the rebuild oracle are l2-space)")
        self.res = ensure_resources(res)
        self._algorithm = algorithm
        self._metric = metric
        self._passes = passes
        self._db_dtype = db_dtype
        self._build_kw = dict(passes=passes, metric=metric, T=T, Qb=Qb,
                              g=g)
        self._n_lists, self._n_probes = n_lists, n_probes
        self._pq_dim, self._pq_bits = pq_dim, pq_bits
        self._threshold = (compact_threshold_default()
                           if compact_threshold is None
                           else max(8, int(compact_threshold)))
        self._cap = (delta_cap_default(self._threshold)
                     if delta_cap is None
                     else max(8, -(-int(delta_cap) // 8) * 8,
                              self._threshold))
        self._auto_compact = bool(auto_compact)

        self._cond = threading.Condition(threading.RLock())
        self._seq = 0
        self._tomb_count = 0
        self._folding = False
        self._fold_thread: Optional[threading.Thread] = None
        self._fold_result = None
        self._compactions = 0

        if isinstance(index, KnnIndex):
            expects(algorithm == "brute",
                    "MutableIndex: a KnnIndex serves algorithm='brute'")
            expects(index.yp is not None,
                    "MutableIndex: the brute plane needs the stored f32"
                    " rows (store_yp=True) — compaction folds from them")
            expects(index.metric == "l2",
                    "MutableIndex: the mutation plane serves "
                    "metric='l2' only")
            plane_idx = index
            self._db_dtype = index.db_dtype
            self._passes = index.passes
            m = index.n_rows
        elif isinstance(index, IvfFlatIndex):
            want = ("ivf_pq" if isinstance(index, IvfPqIndex)
                    else "ivf_flat")
            expects(algorithm == want,
                    "MutableIndex: a prepared %s serves algorithm=%r",
                    type(index).__name__, want)
            expects(index.db_dtype == "f32",
                    "MutableIndex: the mutable IVF plane serves the f32"
                    " slab (int8 IVF stays frozen-index only)")
            plane_idx = index
            m = index.n_rows
        else:
            y = np.asarray(index, np.float32)
            m = y.shape[0]
            plane_idx = self._build_index(y)
        exts = (np.arange(m, dtype=np.int32) if ids is None
                else np.asarray(ids, np.int32))
        expects(exts.shape == (m,),
                "MutableIndex: ids must be [m] external ids")
        expects(exts.size == 0 or int(exts.min()) >= 0,
                "MutableIndex: external ids must be non-negative")
        expects(np.unique(exts).size == exts.size,
                "MutableIndex: external ids must be unique")
        plane = self._make_plane(plane_idx, exts)
        self.d_orig = plane.d_orig
        self.Qb = plane.Qb

        from raft_tpu.serving.snapshot import SnapshotStore

        self._store = SnapshotStore(self._fold_builder,
                                    initial_index=plane)
        self._install_base(plane)
        self._reset_delta()
        self._refresh_delta()

        # durability (off by default — the plane is pure host-side
        # file I/O, so durable=False keeps the hot path untouched)
        self._dur = None
        if durable_dir:
            from raft_tpu.mutable.checkpoint import DurabilityPlane

            self._attach_durability(DurabilityPlane(durable_dir,
                                                    sync=wal_sync))
            # genesis checkpoint: recovery ALWAYS finds a floor, so a
            # WAL record can never exist without a checkpoint under it
            self.checkpoint()

    # -- construction ------------------------------------------------------
    def _build_index(self, y):
        if self._algorithm == "ivf_pq":
            from raft_tpu.ann import build_ivf_pq

            n_lists = self._n_lists or max(
                1, min(1024, int(round(y.shape[0] ** 0.5))))
            return build_ivf_pq(self.res, y, n_lists=n_lists,
                                pq_dim=self._pq_dim,
                                pq_bits=self._pq_bits,
                                n_probes=self._n_probes)
        if self._algorithm == "ivf_flat":
            from raft_tpu.ann import build_ivf_flat

            n_lists = self._n_lists or max(
                1, min(1024, int(round(y.shape[0] ** 0.5))))
            return build_ivf_flat(self.res, y, n_lists=n_lists,
                                  n_probes=self._n_probes)
        from raft_tpu.distance.knn_fused import prepare_knn_index

        kw = dict(self._build_kw)
        if self._db_dtype is not None:
            kw["db_dtype"] = self._db_dtype
        return prepare_knn_index(y, **kw)

    def _make_plane(self, index, exts: np.ndarray) -> _BasePlane:
        if self._algorithm == "brute":
            return _BasePlane("brute", index, exts, _brute_fops(index))
        fops = fused_ops_for_layout(index.layout(), passes=self._passes,
                                    metric="l2")
        return _BasePlane("ivf", index, exts, fops)

    def _fold_builder(self, payload, **_kw):
        rows, exts = payload
        plane = self._make_plane(self._build_index(rows), exts)
        self._fold_result = plane
        return plane

    # -- base tombstone state (reset per swap) -----------------------------
    def _install_base(self, plane: _BasePlane) -> None:
        self._plane = plane
        self._base_rv = plane.fops.rv
        self._base_yyh = plane.fops.ops[plane.fops.yyh_index]
        self._ids_live = (plane.index.ids if plane.kind == "ivf"
                          else None)
        self._base_live = plane.n_rows
        self._tomb_count = 0
        # base lookup: external id → ("base", slab position)
        if plane.kind == "brute":
            skeys = np.arange(plane.n_rows)
        else:
            ids_np = np.asarray(plane.fops.ids)
            slab_pos = np.nonzero(ids_np >= 0)[0]
            # slab position of each global row id
            skeys = np.empty(plane.n_rows, np.int64)
            skeys[ids_np[slab_pos]] = slab_pos
        self._lookup = {int(e): ("base", int(skeys[i]))
                        for i, e in enumerate(plane.exts_np)}

    def _reset_delta(self) -> None:
        self._d_rows = np.zeros((self._cap, self.d_orig), np.float32)
        self._d_ext = np.full(self._cap, -1, np.int32)
        self._d_valid = np.zeros(self._cap, np.bool_)
        self._d_count = 0

    def _refresh_delta(self) -> None:
        """Re-prepare the delta operands (writer-side — readers only
        swap references). The slab shape is FIXED at the cap, so every
        refresh serves from the same compiled programs."""
        layout = dense_layout(self._d_rows, ids=self._d_ext,
                              rows_valid=self._d_valid)
        self._d_fops = fused_ops_for_layout(
            layout, passes=self._passes, metric=self._metric,
            T=_DELTA_T, Qb=_DELTA_QB, g=_DELTA_G,
            db_dtype="int8" if self._db_dtype == "int8" else None)
        self._d_live = int(self._d_valid.sum())

    # -- introspection -----------------------------------------------------
    @property
    def store(self):
        return self._store

    @property
    def generation(self) -> int:
        return self._store.generation

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def n_rows(self) -> int:
        with self._cond:
            return self._base_live + self._d_live

    @property
    def delta_rows(self) -> int:
        with self._cond:
            return self._d_count

    @property
    def delta_cap(self) -> int:
        return self._cap

    @property
    def compact_threshold(self) -> int:
        return self._threshold

    @property
    def compactions(self) -> int:
        with self._cond:
            return self._compactions

    @property
    def folding(self) -> bool:
        with self._cond:
            return self._folding

    def view(self) -> MutableView:
        """A consistent, immutable read view — O(1) reference capture
        under the writer lock. The search path is lock-free after this."""
        with self._cond:
            return MutableView(
                plane=self._plane, base_rv=self._base_rv,
                base_yyh=self._base_yyh, ids_live=self._ids_live,
                base_live=self._base_live, delta_fops=self._d_fops,
                delta_live=self._d_live,
                generation=self._store.generation, seq=self._seq)

    def stats(self) -> dict:
        with self._cond:
            return {
                "generation": self._store.generation,
                "seq": self._seq,
                "base_rows": self._plane.n_rows,
                "base_live": self._base_live,
                "delta_rows": self._d_count,
                "delta_live": self._d_live,
                "delta_cap": self._cap,
                "tombstones": self._tomb_count,
                "compact_threshold": self._threshold,
                "compactions": self._compactions,
                "folding": self._folding,
            }

    # -- mutation internals ------------------------------------------------
    def _tombstone_locked(self, exts: Sequence[int]) -> int:
        """Flip the live bit for every found external id: base rows get
        the never-wins sentinel scattered into the carrier column (+ the
        slab-id mask on IVF); delta slots drop their valid bit. Returns
        how many ids were found. Caller holds the lock."""
        from raft_tpu.ops.fused_l2_topk_pallas import _PACK_PAD

        base_rows, delta_slots, found = [], [], 0
        for e in exts:
            loc = self._lookup.pop(int(e), None)
            if loc is None:
                continue
            found += 1
            if loc[0] == "base":
                base_rows.append(loc[1])
            else:
                delta_slots.append(loc[1])
        if base_rows:
            rows = np.asarray(base_rows, np.int32)
            self._base_rv = self._base_rv.at[rows].set(False)
            self._base_yyh = self._base_yyh.at[:, rows].set(
                float(_PACK_PAD))
            if self._ids_live is not None:
                self._ids_live = self._ids_live.at[rows].set(-1)
            self._base_live -= len(base_rows)
            self._tomb_count += len(base_rows)
        for s in delta_slots:
            self._d_valid[s] = False
        return found

    def _ensure_delta_space_locked(self, n: int) -> None:
        """Block the WRITER until ``n`` delta slots are free — waits for
        an in-flight fold, else folds inline. Readers never wait here."""
        expects(n <= self._cap,
                "MutableIndex: upsert of %d rows exceeds the delta "
                "capacity %d (raise %s)", n, self._cap, DELTA_CAP_ENV)
        while self._cap - self._d_count < n:
            if self._folding:
                self._cond.wait(0.05)
                continue
            # inline fold on the writer thread — the delta is full and
            # nobody else is folding
            upto = self._begin_fold_locked()
            self._cond.release()
            try:
                self._fold(upto)
            finally:
                self._cond.acquire()

    def _mutation_epilogue_locked(self, kind: str, n: int) -> None:
        self._seq += 1
        self._refresh_delta()
        try:
            self.res.metrics.counter(
                MUTATIONS, {"kind": kind},
                help="Mutable-index mutations applied").inc(n)
        except Exception:
            pass
        _gauges(self.res.metrics, self._d_count, self._cap,
                self._tomb_count, self._plane.n_rows, self._threshold)
        emit_mutation(kind, rows=n, seq=self._seq,
                      delta_rows=self._d_count, delta_live=self._d_live,
                      tombstones=self._tomb_count,
                      generation=self._store.generation)

    def _upsert(self, exts: np.ndarray, rows: np.ndarray) -> int:
        n = rows.shape[0]
        with self._cond:
            self._ensure_delta_space_locked(n)
            if self._dur is not None:
                # write-ahead: the record lands in the WAL before any
                # in-memory state changes (an append failure leaves
                # the index untouched; a crash after it replays a
                # submitted-but-unacked write in FULL — never half)
                self._dur.log_upsert(exts, rows)
            self._tombstone_locked(exts)          # old copies, any plane
            c = self._d_count
            self._d_rows[c:c + n] = rows
            self._d_ext[c:c + n] = exts
            self._d_valid[c:c + n] = True
            for i, e in enumerate(exts):
                self._lookup[int(e)] = ("delta", c + i)
            self._d_count = c + n
            self._mutation_epilogue_locked("upsert", n)
        if self._dur is not None:
            self._dur.commit()       # the fsync horizon — ack AFTER it
        self._maybe_compact()
        return n

    def _delete(self, exts: np.ndarray) -> int:
        with self._cond:
            if self._dur is not None:
                self._dur.log_delete(exts)
            found = self._tombstone_locked(exts)
            self._mutation_epilogue_locked("delete", found)
        if self._dur is not None:
            self._dur.commit()
        self._maybe_compact()
        return found

    # -- durability --------------------------------------------------------
    @property
    def durability(self):
        """The attached DurabilityPlane (None = the in-memory index)."""
        return self._dur

    def _attach_durability(self, plane) -> None:
        self._dur = plane

    def checkpoint(self) -> Optional[str]:
        """Commit one atomic full-state checkpoint (live base + live
        delta + the current LSN watermark, captured consistently under
        the writer lock; files written outside it). No-op without a
        durability plane. The compactor calls this at every swap."""
        if self._dur is None:
            return None
        with self._cond:
            rows, exts = self._materialize_locked(self._d_count)
            lsn = self._dur.wal.last_lsn
            gen = self._store.generation
        return self._dur.checkpoint(rows, exts, lsn, gen)

    def close(self) -> None:
        """Flush + close the durability plane (no-op when in-memory).
        The index itself stays queryable; further durable mutations
        need a fresh attach (``checkpoint.recover``)."""
        if self._dur is not None:
            self._dur.close()
            self._dur = None

    # -- compaction --------------------------------------------------------
    def _begin_fold_locked(self) -> int:
        self._folding = True
        return self._d_count

    def _maybe_compact(self) -> None:
        with self._cond:
            if (not self._auto_compact or self._folding
                    or self._d_count < self._threshold):
                return
            upto = self._begin_fold_locked()
            t = threading.Thread(target=self._fold_guarded, args=(upto,),
                                 name="mutable-compactor", daemon=True)
            self._fold_thread = t
        t.start()

    def compact(self, block: bool = True) -> bool:
        """Fold (live base + live delta) into a fresh base snapshot.
        ``block=True`` folds inline and returns whether a swap landed;
        ``block=False`` starts the background compactor (the auto
        trigger's path) and returns True when one was started. A fold
        already in flight is waited for (block) or left alone."""
        with self._cond:
            if self._folding:
                if not block:
                    return True
                while self._folding:
                    self._cond.wait(0.05)
                return self._fold_result is not None
            upto = self._begin_fold_locked()
            if not block:
                t = threading.Thread(target=self._fold_guarded,
                                     args=(upto,),
                                     name="mutable-compactor",
                                     daemon=True)
                self._fold_thread = t
        if not block:
            t.start()
            return True
        self._fold(upto)
        return self._fold_result is not None

    def wait_for_compaction(self, timeout: Optional[float] = None) -> None:
        t = self._fold_thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def _fold_guarded(self, upto: int) -> None:
        """Background-compactor wrapper: a crash is logged + counted,
        never propagated (the old snapshot keeps serving)."""
        try:
            self._fold(upto)
        except Exception as e:
            from raft_tpu.core.logger import log_warn

            log_warn("mutable: background compaction failed (%s: %s) — "
                     "keeping the current snapshot",
                     type(e).__name__, str(e)[:200])

    def _count_compaction(self, status: str) -> None:
        try:
            self.res.metrics.counter(
                COMPACTIONS, {"status": status},
                help="Mutable-index compaction folds by outcome").inc()
        except Exception:
            pass

    def _fold(self, upto: int) -> None:
        """One compaction cycle: materialize the live rows as of entry,
        rebuild through the warmed rebuild-and-swap, then rebase the
        retained delta tail + mid-fold mutations onto the new base.
        Caller must have set ``_folding`` (``_begin_fold_locked``)."""
        self._fold_result = None
        try:
            fault_point("compact_fold")
            with self._cond:
                gen0 = self._store.generation
                emit_mutation("compact_start", generation=gen0,
                              delta_rows=upto,
                              tombstones=self._tomb_count)
                rows, exts = self._materialize_locked(upto)
            # the EXPENSIVE part — outside the lock: readers keep the
            # old view, writers keep appending past `upto`
            self._store.update((rows, exts), block=True)
            plane = self._fold_result
            if plane is None:
                raise RuntimeError(
                    self._store.last_error
                    or "snapshot rebuild failed during compaction")
            with self._cond:
                self._rebase_locked(plane, upto)
                self._compactions += 1
                _gauges(self.res.metrics, self._d_count, self._cap,
                        self._tomb_count, self._plane.n_rows,
                        self._threshold)
                emit_mutation("compact_swap",
                              generation=self._store.generation,
                              folded_rows=int(rows.shape[0]),
                              retained_delta=self._d_count)
            if self._dur is not None:
                try:
                    # bound the next recovery's tail at the swap; a
                    # failed checkpoint keeps the older one + a longer
                    # WAL tail — degraded, never lost
                    self.checkpoint()
                except Exception as e:
                    from raft_tpu.core.logger import log_warn

                    log_warn("mutable: post-fold checkpoint failed "
                             "(%s: %s) — WAL tail keeps covering the "
                             "delta", type(e).__name__, str(e)[:200])
            self._count_compaction("ok")
        except Exception:
            self._count_compaction("failed")
            with self._cond:
                emit_mutation("compact_abort",
                              generation=self._store.generation)
            raise
        finally:
            with self._cond:
                self._folding = False
                self._cond.notify_all()

    def _materialize_locked(self, upto: int
                            ) -> Tuple[np.ndarray, np.ndarray]:
        """The fold input: live base rows + live delta rows in slots
        [0, upto), in deterministic (base order, then append order)."""
        plane = self._plane
        if plane.kind == "brute":
            live = np.asarray(self._base_rv)[:plane.n_rows]
            # yp is the d-PADDED prepared slab — fold from the original
            # feature width (zero pad columns are re-derived at build)
            base_rows = np.asarray(
                plane.index.yp)[:plane.n_rows, :plane.d_orig][live]
            base_exts = plane.exts_np[live]
        else:
            ids_live = np.asarray(self._ids_live)
            pos = np.nonzero(ids_live >= 0)[0]
            order = np.argsort(ids_live[pos], kind="stable")
            pos = pos[order]                       # original row order
            base_rows = np.asarray(plane.index.slab)[pos]
            base_exts = plane.exts_np[ids_live[pos]]
        dl = self._d_valid[:upto]
        rows = np.concatenate([base_rows, self._d_rows[:upto][dl]])
        exts = np.concatenate([base_exts, self._d_ext[:upto][dl]])
        return np.ascontiguousarray(rows), np.ascontiguousarray(exts)

    def _rebase_locked(self, plane: _BasePlane, upto: int) -> None:
        """Install the folded base and replay everything that happened
        mid-fold: the live ``_lookup`` is the single source of truth —
        a folded copy whose external id now lives elsewhere (re-upserted
        into the retained delta) or nowhere (deleted) is tombstoned in
        the NEW base before it ever serves."""
        old_lookup = self._lookup
        retained = [(self._d_rows[s].copy(), int(self._d_ext[s]),
                     bool(self._d_valid[s]))
                    for s in range(upto, self._d_count)]
        self._install_base(plane)                  # fresh lookup/masks
        # replay the mid-fold mutations: the pre-swap lookup is the
        # single source of truth — a folded copy is live only if its
        # external id still pointed at the folded content (the old
        # base, or a delta slot below the fold line) at swap time;
        # anything else (deleted, or re-upserted into the retained
        # tail) is tombstoned in the NEW base before it ever serves
        stale = []
        for e in list(self._lookup):
            loc = old_lookup.get(e)
            folded_is_live = loc is not None and (
                loc[0] == "base" or (loc[0] == "delta"
                                     and loc[1] < upto))
            if not folded_is_live:
                stale.append(e)
        if stale:
            self._tombstone_locked(stale)
        # retained delta tail → front of a fresh delta
        self._reset_delta()
        for row, ext, valid in retained:
            s = self._d_count
            self._d_rows[s] = row
            self._d_ext[s] = ext
            self._d_valid[s] = valid
            if valid:
                self._lookup[ext] = ("delta", s)
            self._d_count = s + 1
        self._seq += 1
        self._refresh_delta()


# ------------------------------------------------------- module entry ops
@instrument("mutable.apply_upsert")
def apply_upsert(index: MutableIndex, ids, rows) -> int:
    """Upsert ``rows`` [n, d] under external ``ids`` [n]: existing
    copies are tombstoned, the new rows land in the delta slab —
    quantized/certified on ingest when the base streams int8 — and the
    change is visible to the next search. Returns the applied count.
    Carries the ``mutate_ingest`` fault site (before any state change:
    an injected crash leaves the index untouched)."""
    fault_point("mutate_ingest")
    rows = np.asarray(rows, np.float32)
    if rows.ndim == 1:
        rows = rows[None]
    ids = np.atleast_1d(np.asarray(ids, np.int32))
    expects(rows.ndim == 2 and rows.shape[1] == index.d_orig,
            "apply_upsert: rows must be [n, %d] (got %s)", index.d_orig,
            rows.shape)
    expects(ids.shape[0] == rows.shape[0],
            "apply_upsert: ids/rows length mismatch (%d vs %d)",
            ids.shape[0], rows.shape[0])
    expects(ids.size == 0 or int(ids.min()) >= 0,
            "apply_upsert: external ids must be non-negative")
    expects(np.unique(ids).size == ids.size,
            "apply_upsert: duplicate external ids in one batch")
    return index._upsert(ids, rows)


@instrument("mutable.apply_delete")
def apply_delete(index: MutableIndex, ids) -> int:
    """Delete the rows under external ``ids``: a tombstone-bitmap flip
    + a never-wins sentinel scatter — the slab is untouched and the
    delete is visible to the next search. Returns how many ids were
    found. Carries the ``tombstone_apply`` fault site."""
    fault_point("tombstone_apply")
    ids = np.atleast_1d(np.asarray(ids, np.int32))
    return index._delete(ids)


def _pad_pool(vals, ids, k: int):
    """Widen a [nq, k'] pool to k columns with (inf, −1) riders — a
    slab with fewer than k live rows searches at k' = live (asking for
    more would leave θ = inf and fail EVERY certificate into the
    fixup, whose dot_general rounds differently than the rescore) and
    pads back up for the rank-major merge."""
    import jax.numpy as jnp

    pad = k - vals.shape[1]
    if pad <= 0:
        return vals, ids
    nq = vals.shape[0]
    return (jnp.concatenate(
        [vals, jnp.full((nq, pad), jnp.inf, vals.dtype)], axis=1),
        jnp.concatenate(
            [ids, jnp.full((nq, pad), -1, jnp.int32)], axis=1))


def _mutable_ivf_chunk(base, ids_live, xs, pr, st, ps, k: int, P: int,
                       W: int):
    """One tombstone-masked base-plane IVF chunk: the flat probe
    gather with the masked slab ids, or — on a PQ base — the ADC
    codes-slab scan with the same masked ids (a tombstone masks the
    CODES slab without a repack: the pooled candidate simply rescores
    to +inf, and a certificate failure reruns the equally-masked f32
    scan, so a deleted row can never resurface either way)."""
    import jax.numpy as jnp

    from raft_tpu.ann.ivf_flat import _fine_scan
    from raft_tpu.ann.ivf_pq import IvfPqIndex, pq_scan_chunk

    if isinstance(base, IvfPqIndex):
        vals, gids, ok, _margin = pq_scan_chunk(
            base, xs, np.asarray(pr), pr, st, ps, k, P, W,
            ids=ids_live)
        n_fail = int(jnp.sum(~ok))
        if n_fail:
            fv, fi = _fine_scan(xs, base.slab, ids_live, base.yy_slab,
                                st, ps, k=k, P=P, W=W)
            okc = ok[:, None]
            vals = jnp.where(okc, vals, fv)
            gids = jnp.where(okc, gids, fi)
        return vals, gids
    return _fine_scan(xs, base.slab, ids_live, base.yy_slab, st, ps,
                      k=k, P=P, W=W)


def _search_base(view: MutableView, x, k: int, exact: bool,
                 n_probes: Optional[int], res):
    """Top-k over the (tombstone-masked) base plane → (vals, EXTERNAL
    ids, n_fail device or None)."""
    import jax.numpy as jnp

    plane = view.plane
    k = min(k, view.base_live)
    if plane.kind == "ivf" and not exact:
        base = plane.index
        L = base.n_lists
        P = int(n_probes) if n_probes else base.n_probes_default
        if P < L:
            from raft_tpu.ann.ivf_flat import _FINE_TILE, _coarse_probe

            W = base.probe_window
            if k <= P * W:
                probes = _coarse_probe(res, base.centroids, x, P)
                starts = jnp.take(base.offsets[:-1], probes)
                psizes = jnp.take(base.padded_sizes, probes)
                d = x.shape[1]
                chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
                outs = []
                for s in range(0, x.shape[0], chunk):
                    v, g = _mutable_ivf_chunk(
                        base, view.ids_live, x[s:s + chunk],
                        probes[s:s + chunk], starts[s:s + chunk],
                        psizes[s:s + chunk], k, P, W)
                    outs.append((v, g))
                vals = jnp.concatenate([o[0] for o in outs])
                gids = jnp.concatenate([o[1] for o in outs])
                ext = jnp.where(gids >= 0,
                                jnp.take(plane.ext_row,
                                         jnp.maximum(gids, 0)), -1)
                return vals, ext, None
        # degenerate regime (n_probes >= n_lists / k over capacity):
        # fall through to the certified exact scan below
    vals, pos, n_fail = run_fused_ops(plane.fops, x, k,
                                      rows_valid=view.base_rv,
                                      yyh_k=view.base_yyh)
    ext = jnp.where(pos >= 0,
                    jnp.take(plane.ext_slab, jnp.maximum(pos, 0)), -1)
    return vals, ext, n_fail


@instrument("mutable.search_view")
def search_view(index, x, k: int, view: Optional[MutableView] = None,
                n_probes: Optional[int] = None, exact: bool = False,
                res=None) -> Tuple:
    """Certified top-k over one consistent :class:`MutableView` (taken
    from ``index`` when not given): the tombstone-masked base and the
    delta tail each produce a top-k pool and the two merge rank-major
    (the PR-4 merge) — exact values, ids identical to a from-scratch
    rebuild over the live rows. Returns (vals [nq, k] ascending,
    EXTERNAL ids [nq, k]; −1 entries pad when fewer than k rows are
    live). ``exact=True`` forces the IVF plane through the certified
    exact scan (the shadow-sampling oracle's switch)."""
    import jax.numpy as jnp

    from raft_tpu.distance.knn_sharded import _merge_host_pool

    if view is None:
        view = index.view() if isinstance(index, MutableIndex) else index
    mi = index if isinstance(index, MutableIndex) else None
    if res is None and mi is not None:
        res = mi.res
    x = jnp.asarray(x, jnp.float32)
    expects(x.ndim == 2 and x.shape[1] == view.plane.d_orig,
            "search_view: queries must be [nq, %d] (got %s)",
            view.plane.d_orig, x.shape)
    expects(k >= 1, "search_view: k must be >= 1")
    nq = x.shape[0]
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    pools = []
    if view.base_live > 0:
        bv, bi, nf = _search_base(view, x, k, exact, n_probes, res)
        pools.append(_pad_pool(bv, bi, k))
        if nf is not None:
            from raft_tpu.distance.knn_fused import (fixup_tiers_for,
                                                     rescore_pool_width)

            fops = view.plane.fops
            record_pending(
                "mutable.search_base", nf, n_queries=nq,
                pool_width=rescore_pool_width(k, fops.pool_width // 2,
                                              True),
                fix_tiers=fixup_tiers_for(fops.slab_rows),
                db_dtype=fops.db_dtype, generation=view.generation)
    if view.delta_live > 0:
        kd = min(k, view.delta_live)
        dv, dpos, nf = run_fused_ops(view.delta_fops, x, kd)
        di = jnp.where(dpos >= 0,
                       jnp.take(view.delta_fops.ids,
                                jnp.maximum(dpos, 0)), -1)
        pools.append(_pad_pool(dv, di, k))
        from raft_tpu.distance.knn_fused import (fixup_tiers_for,
                                                 rescore_pool_width)

        record_pending(
            "mutable.search_delta", nf, n_queries=nq,
            pool_width=rescore_pool_width(
                k, view.delta_fops.pool_width // 2, True),
            fix_tiers=fixup_tiers_for(view.delta_fops.slab_rows),
            db_dtype=view.delta_fops.db_dtype, generation=view.generation)
    if not pools:
        return (jnp.full((nq, k), jnp.inf, jnp.float32),
                jnp.full((nq, k), -1, jnp.int32))
    if len(pools) == 1:
        return pools[0]
    # two-slab rank-major merge: (base, delta) pool order is fixed, so
    # the result is deterministic — and bit-identical to one top-k over
    # the concatenated live rows (the rebuild-oracle parity the tests
    # pin)
    gv = jnp.stack([p[0] for p in pools])
    gi = jnp.stack([p[1] for p in pools])
    return _merge_host_pool(gv, gi, k)
