"""Segmented write-ahead log for the mutation plane (ISSUE 12).

Until this PR ``MutableIndex`` lived entirely in memory: a crash lost
every acked upsert/delete since boot. This module is the durability
floor under it — every mutation is framed, CRC-protected and (per the
sync policy) fsynced BEFORE the caller's ack, so "acked" finally means
"survives a crash".

Record frame (little-endian)::

    magic   4B  b"RWL1"
    version u16 schema version (1)
    op      u8  1=upsert  2=delete  3=checkpoint-mark
    flags   u8  reserved (0)
    lsn     u64 monotone log sequence number (1-based)
    plen    u32 payload length
    payload plen bytes (framed mdspans via core.serialize — upserts
                carry (ids, rows), deletes carry ids, checkpoint marks
                carry a small JSON blob)
    crc32   u32 over magic..payload

Properties the recovery proof leans on:

- **atomic records** — a frame is written with one ``write``; a torn
  tail (partial frame, bad CRC, bad magic) marks the END of the valid
  log. :func:`replay` stops at the first bad frame and (with
  ``truncate=True``) physically truncates after the last good one —
  the plan-cache contract: corrupt degrades, never raises.
- **monotone LSNs** — a duplicate or regressing LSN is treated exactly
  like a CRC failure (a corruption boundary), so replay can never
  double-apply.
- **group commit** — ``RAFT_TPU_WAL_SYNC`` ∈ ``{always, batch, none}``:
  ``always`` fsyncs per record, ``batch`` (default) fsyncs once per
  :meth:`WalWriter.commit` (one fsync covers every record of a
  mutation request — the ack horizon ``MutableIndex`` waits on),
  ``none`` never fsyncs (throughput mode; acked ≠ durable, says so in
  the README).
- **segment rotation + retirement** — segments are
  ``wal-<first-lsn>.log`` files capped at ``RAFT_TPU_WAL_SEGMENT_MB``;
  once a checkpoint's LSN watermark covers a whole non-active segment,
  :meth:`WalWriter.retire_through` deletes it.

Fault sites ``wal_append`` / ``wal_fsync`` (gated like the existing 22
by ``tools/check_instrumented.py``) make both halves of the durability
promise injectable — and the crash matrix in tests/test_durability.py
SIGKILLs a subprocess at each of them.
"""

from __future__ import annotations

import glob
import json
import os
import struct
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from raft_tpu.core import env
from raft_tpu.core.serialize import mdspan_to_bytes, read_framed
from raft_tpu.resilience import fault_point

WAL_MAGIC = b"RWL1"
WAL_VERSION = 1
OP_UPSERT, OP_DELETE, OP_CHECKPOINT = 1, 2, 3
_OP_NAMES = {OP_UPSERT: "upsert", OP_DELETE: "delete",
             OP_CHECKPOINT: "checkpoint-mark"}

_HEADER = struct.Struct("<4sHBBQI")
_CRC = struct.Struct("<I")

SYNC_MODES = ("always", "batch", "none")
WAL_SYNC_ENV = "RAFT_TPU_WAL_SYNC"
WAL_SEGMENT_MB_ENV = "RAFT_TPU_WAL_SEGMENT_MB"
_DEFAULT_SEGMENT_MB = 64

# the WAL slice of the metric vocabulary
WAL_APPENDS = "raft_tpu_wal_appends_total"
WAL_FSYNCS = "raft_tpu_wal_fsyncs_total"
WAL_BYTES = "raft_tpu_wal_bytes_total"
WAL_DURABLE_LSN = "raft_tpu_wal_durable_lsn"
WAL_SEGMENTS = "raft_tpu_wal_segments"


def sync_mode_default() -> str:
    """``RAFT_TPU_WAL_SYNC`` resolved to a valid mode (default
    ``batch``; an unknown value degrades to the default with a logged
    warning — never raises at import/construction)."""
    raw = (env.raw(WAL_SYNC_ENV) or "").lower()
    if not raw:
        return "batch"
    if raw in SYNC_MODES:
        return raw
    from raft_tpu.core.logger import log_warn

    log_warn("%s=%r is not one of %s — using 'batch'", WAL_SYNC_ENV,
             raw, SYNC_MODES)
    return "batch"


def segment_bytes_default() -> int:
    mb = env.get(WAL_SEGMENT_MB_ENV, float(_DEFAULT_SEGMENT_MB))
    return max(1 << 16, int(mb * (1 << 20)))


class WalRecord(NamedTuple):
    """One decoded log record."""

    op: int
    lsn: int
    payload: bytes

    @property
    def op_name(self) -> str:
        return _OP_NAMES.get(self.op, f"op{self.op}")


# ------------------------------------------------------------ payloads
def encode_upsert(ids, rows) -> bytes:
    """Upsert payload: two framed mdspans back to back (ids int32,
    rows f32) — ``core.serialize`` frames are self-delimiting."""
    return (mdspan_to_bytes(np.asarray(ids, np.int32))
            + mdspan_to_bytes(np.asarray(rows, np.float32)))


def decode_upsert(payload: bytes) -> Tuple[np.ndarray, np.ndarray]:
    ids, off = read_framed(payload)
    rows, _ = read_framed(payload, off)
    return ids.as_numpy(), rows.as_numpy()


def encode_delete(ids) -> bytes:
    return mdspan_to_bytes(np.asarray(ids, np.int32))


def decode_delete(payload: bytes) -> np.ndarray:
    ids, _ = read_framed(payload)
    return ids.as_numpy()


def encode_checkpoint_mark(lsn: int, generation: int,
                           name: str = "") -> bytes:
    return json.dumps({"lsn": int(lsn), "generation": int(generation),
                       "name": name}).encode()


# -------------------------------------------------------------- frames
def encode_frame(op: int, lsn: int, payload: bytes) -> bytes:
    """One atomic frame: header + payload + CRC32 trailer."""
    head = _HEADER.pack(WAL_MAGIC, WAL_VERSION, op, 0, lsn,
                        len(payload))
    return head + payload + _CRC.pack(
        zlib.crc32(head + payload) & 0xFFFFFFFF)


def _read_frame(data: bytes, off: int):
    """-> ("ok", WalRecord, next_off) | ("eof",) | ("corrupt", reason).
    ``eof`` only at an EXACT frame boundary; anything else that fails
    to parse is a corruption/torn-tail boundary."""
    if off == len(data):
        return ("eof",)
    if len(data) - off < _HEADER.size:
        return ("corrupt", "short frame header")
    magic, version, op, _flags, lsn, plen = _HEADER.unpack_from(data,
                                                                off)
    if magic != WAL_MAGIC:
        return ("corrupt", f"bad magic {magic!r}")
    if version > WAL_VERSION:
        return ("corrupt", f"future schema version {version}")
    body_end = off + _HEADER.size + plen
    if len(data) < body_end + _CRC.size:
        return ("corrupt", "short frame body")
    (crc,) = _CRC.unpack_from(data, body_end)
    if crc != (zlib.crc32(data[off:body_end]) & 0xFFFFFFFF):
        return ("corrupt", "CRC mismatch")
    rec = WalRecord(op, lsn, bytes(data[off + _HEADER.size:body_end]))
    return ("ok", rec, body_end + _CRC.size)


def _segment_paths(directory: str) -> List[str]:
    """Segment files in LSN order (name-sortable zero-padded names)."""
    return sorted(glob.glob(os.path.join(directory, "wal-*.log")))


def _segment_name(first_lsn: int) -> str:
    return f"wal-{first_lsn:016d}.log"


# -------------------------------------------------------------- writer
class WalWriter:
    """Appender over a directory of log segments. Thread-safe (the
    mutation path and the compactor both append). With the registry
    disabled / no metrics the hot path is append + optional fsync —
    no jax, no dispatches, no compile-cache traffic (the durable=False
    parity the serving gate pins)."""

    def __init__(self, directory: str, sync: Optional[str] = None,
                 segment_bytes: Optional[int] = None,
                 next_lsn: int = 1):
        self.directory = directory
        self.sync = sync_mode_default() if sync is None else str(sync)
        if self.sync not in SYNC_MODES:
            raise ValueError(f"WalWriter: sync must be one of "
                             f"{SYNC_MODES}, got {self.sync!r}")
        self.segment_bytes = (segment_bytes_default()
                              if segment_bytes is None
                              else max(1 << 10, int(segment_bytes)))
        self._lock = threading.Lock()
        self._next_lsn = max(1, int(next_lsn))
        self._durable_lsn = self._next_lsn - 1
        self._dirty = False
        self._f = None
        self._seg_written = 0
        os.makedirs(directory, exist_ok=True)
        self._open_segment_locked()

    # -- internals ---------------------------------------------------------
    def _open_segment_locked(self) -> None:
        path = os.path.join(self.directory,
                            _segment_name(self._next_lsn))
        self._f = open(path, "ab")
        self._seg_written = self._f.tell()

    def _rotate_locked(self) -> None:
        self._fsync_locked(force=self.sync != "none")
        self._f.close()
        self._open_segment_locked()

    def _fsync_locked(self, force: bool = False) -> None:
        if not self._dirty and not force:
            return
        self._f.flush()
        if self.sync != "none" or force:
            fault_point("wal_fsync")
            os.fsync(self._f.fileno())
            self._count(WAL_FSYNCS, 1)
        self._dirty = False
        self._durable_lsn = self._next_lsn - 1
        self._gauge(WAL_DURABLE_LSN, self._durable_lsn,
                    "Highest fsynced WAL log sequence number")

    @staticmethod
    def _count(name: str, n: int, **labels) -> None:
        try:
            from raft_tpu.observability import get_registry

            get_registry().counter(
                name, labels or None,
                help="Write-ahead-log activity").inc(n)
        except Exception:
            pass

    @staticmethod
    def _gauge(name: str, value: float, help: str) -> None:
        try:
            from raft_tpu.observability import get_registry

            get_registry().gauge(name, help=help).set(value)
        except Exception:
            pass

    # -- API ---------------------------------------------------------------
    @property
    def last_lsn(self) -> int:
        """Highest ASSIGNED lsn (durable only up to
        :attr:`durable_lsn` until the next commit)."""
        with self._lock:
            return self._next_lsn - 1

    @property
    def durable_lsn(self) -> int:
        with self._lock:
            return self._durable_lsn

    def append(self, op: int, payload: bytes) -> int:
        """Append one record; returns its lsn. ``sync="always"``
        fsyncs inline; otherwise durability waits for :meth:`commit`
        (the group-commit horizon). Carries the ``wal_append`` fault
        site BEFORE any byte is written — an injected failure leaves
        the log untouched."""
        fault_point("wal_append")
        with self._lock:
            lsn = self._next_lsn
            frame = encode_frame(op, lsn, payload)
            self._f.write(frame)
            self._next_lsn = lsn + 1
            self._dirty = True
            self._seg_written += len(frame)
            self._count(WAL_APPENDS, 1, op=_OP_NAMES.get(op, str(op)))
            self._count(WAL_BYTES, len(frame))
            if self.sync == "always":
                self._fsync_locked()
            if self._seg_written >= self.segment_bytes:
                self._rotate_locked()
        return lsn

    def commit(self) -> int:
        """Make everything appended so far durable (per the sync mode);
        returns the durable lsn horizon — the ack line: a mutation is
        acknowledged only after its lsn ≤ this value (``sync="none"``
        flushes to the OS but skips the fsync — documented as the
        throughput mode that trades the ack contract away)."""
        with self._lock:
            if self._dirty:
                if self.sync == "none":
                    self._f.flush()
                    self._dirty = False
                    self._durable_lsn = self._next_lsn - 1
                else:
                    self._fsync_locked()
            return self._durable_lsn

    def rotate(self) -> None:
        """Start a new segment (checkpoints rotate so the previous
        segment becomes retirable once the watermark covers it)."""
        with self._lock:
            if self._seg_written:
                self._rotate_locked()

    def retire_through(self, watermark_lsn: int) -> int:
        """Delete whole segments whose every record has lsn ≤ the
        checkpoint ``watermark_lsn``; the active segment always stays.
        Returns how many were removed."""
        # the directory scan + unlinks run OUTSIDE the append lock
        # (graftlint blocking-under-lock): segment GC touches the disk
        # and must never stall a mutation ack behind it. Lock-free is
        # safe here: rotation only ADDS newer segments (the active one
        # is always last and `paths[:-1]` never touches it), and a
        # concurrent retire losing an unlink race stops at the OSError.
        removed = 0
        paths = _segment_paths(self.directory)
        for i, path in enumerate(paths[:-1]):
            # segment i ends just before segment i+1's first lsn
            nxt = os.path.basename(paths[i + 1])
            try:
                next_first = int(nxt[len("wal-"):-len(".log")])
            except ValueError:
                break
            if next_first - 1 > watermark_lsn:
                break
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                break
        self._gauge(WAL_SEGMENTS,
                    len(_segment_paths(self.directory)),
                    "Live WAL segment files")
        if removed:
            try:
                from raft_tpu.observability.timeline import emit_marker

                emit_marker("wal_retire", segments=removed,
                            watermark_lsn=int(watermark_lsn))
            except Exception:
                pass
        return removed

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._fsync_locked(force=self.sync != "none")
                finally:
                    self._f.close()
                    self._f = None

    def stats(self) -> Dict:
        # the segment count is a disk scan — taken OUTSIDE the append
        # lock (graftlint blocking-under-lock) so a statusz poll on a
        # slow disk can never stall the mutation ack path
        segments = len(_segment_paths(self.directory))
        with self._lock:
            return {
                "sync": self.sync,
                "last_lsn": self._next_lsn - 1,
                "durable_lsn": self._durable_lsn,
                "segments": segments,
                "segment_bytes": self.segment_bytes,
            }


# -------------------------------------------------------------- replay
def replay(directory: str, from_lsn: int = 0,
           truncate: bool = False) -> Tuple[List[WalRecord], Dict]:
    """Scan the log; returns (records with ``lsn > from_lsn`` excluding
    checkpoint marks, stats). NEVER raises: a bad CRC, short frame,
    unreadable segment, or duplicate/regressing lsn is a corruption
    boundary — replay stops there, counts everything after it as
    ``truncated_bytes``, and (``truncate=True`` — the recovery path)
    physically truncates the torn tail + deletes later segments so new
    appends never interleave with garbage."""
    records: List[WalRecord] = []
    stats = {"records": 0, "last_lsn": 0, "truncated_bytes": 0,
             "segments": 0, "stopped_early": False, "stop_reason": ""}
    paths = _segment_paths(directory) if os.path.isdir(directory) else []
    stats["segments"] = len(paths)
    last_lsn = None
    stopped = False
    for i, path in enumerate(paths):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            stopped = True
            stats["stop_reason"] = f"unreadable segment: {e}"
            stats["truncated_bytes"] += sum(
                _size_of(p) for p in paths[i:])
            break
        off = 0
        while True:
            out = _read_frame(data, off)
            if out[0] == "eof":
                break
            if out[0] == "corrupt":
                stopped = True
                stats["stop_reason"] = out[1]
            else:
                rec, noff = out[1], out[2]
                if last_lsn is not None and rec.lsn <= last_lsn:
                    stopped = True
                    stats["stop_reason"] = (
                        f"lsn {rec.lsn} does not advance past "
                        f"{last_lsn} (duplicate/regressing)")
                else:
                    last_lsn = rec.lsn
                    if rec.lsn > from_lsn and rec.op != OP_CHECKPOINT:
                        records.append(rec)
                    off = noff
                    continue
            # corruption boundary: count + optionally truncate the
            # tail of THIS segment, drop every later segment
            stats["truncated_bytes"] += len(data) - off
            if truncate:
                try:
                    with open(path, "r+b") as f:
                        f.truncate(off)
                except OSError:
                    pass
            break
        if stopped:
            for later in paths[i + 1:]:
                stats["truncated_bytes"] += _size_of(later)
                if truncate:
                    try:
                        os.unlink(later)
                    except OSError:
                        pass
            break
    stats["records"] = len(records)
    stats["last_lsn"] = last_lsn or 0
    stats["stopped_early"] = stopped
    return records, stats


def _size_of(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0
