"""raft_tpu — a TPU-native primitives framework with the capabilities of
rapidsai/raft, built from scratch on JAX/XLA/Pallas/pjit.

The reference (mounted at /root/reference, v26.08.00) is a CUDA/C++ header
library; this package is NOT a port of it. It re-designs the same capability
surface TPU-first:

- ``raft_tpu.core``     — resources registry / handle system, mdarray-style
  data layer over ``jax.Array``, bitset/bitmap, serialization, logging,
  tracing, cooperative interruption.  (ref: cpp/include/raft/core)
- ``raft_tpu.linalg``   — dense linear algebra: map/reduce, norms, BLAS,
  QR/eig/SVD, randomized SVD, least squares, PCA/TSVD.
  (ref: cpp/include/raft/linalg)
- ``raft_tpu.matrix``   — matrix manipulation + batched ``select_k`` top-k.
  (ref: cpp/include/raft/matrix)
- ``raft_tpu.sparse``   — COO/CSR formats, sparse linalg, Lanczos /
  randomized-SVD / MST solvers.  (ref: cpp/include/raft/sparse)
- ``raft_tpu.spectral`` — graph Laplacian / modularity analysis + embedding.
- ``raft_tpu.solver``   — linear assignment.  (ref: cpp/include/raft/solver)
- ``raft_tpu.label``    — label compaction / merging.
- ``raft_tpu.random``   — counter-based device RNG + dataset generators.
- ``raft_tpu.stats``    — statistics and model metrics.
- ``raft_tpu.distance`` — pairwise distances + fused L2 nearest-neighbor
  (pre-cuVS RAFT surface, rebuilt TPU-first).
- ``raft_tpu.comms``    — the NCCL/UCX ``comms_t`` vocabulary re-imagined
  over ``jax.lax`` collectives on a device mesh (ICI/DCN).
- ``raft_tpu.parallel`` — mesh/sharding helpers, multi-host session.
- ``raft_tpu.models``   — estimator-style wrappers (PCA, TSVD, spectral
  embedding, brute-force KNN).
- ``raft_tpu.ops``      — Pallas TPU kernels for the hot paths.
- ``raft_tpu.observability`` — unified metrics + span tracing (counters/
  gauges/histograms, nvtx-attributed spans, Prometheus/JSONL exporters).
  (ref: core/nvtx.hpp + mr/resource_monitor.hpp, unified)
"""

import jax as _jax

# jax promoted shard_map out of jax.experimental (~0.5); the sharded
# primitives are written against the new ``jax.shard_map`` spelling.
# Alias it on older jax so the comms/sharded layers (and their tier-1
# coverage) work on both sides of the promotion — this package is always
# imported before any submodule, so one gated alias covers every call
# site. (Same pattern as the pltpu.CompilerParams shim in ops/utils.py.)
if not hasattr(_jax, "shard_map"):
    try:
        import functools as _functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @_functools.wraps(_shard_map)
        def _compat_shard_map(*args, **kwargs):
            # new-jax kwarg spelling → old (check_vma was check_rep)
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)

        _jax.shard_map = _compat_shard_map
    except ImportError:
        pass

from raft_tpu.version import __version__

from raft_tpu.core import (
    Resources,
    DeviceResources,
    device_resources,
)

__all__ = [
    "__version__",
    "Resources",
    "DeviceResources",
    "device_resources",
]
