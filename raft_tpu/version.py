"""Version of raft_tpu. Mirrors the reference snapshot it tracks
(/root/reference VERSION = 26.08.00) with an independent scheme."""

__version__ = "0.1.0"
RAFT_REFERENCE_VERSION = "26.08.00"
