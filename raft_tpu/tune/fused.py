"""Autotuner for the fused L2 top-k pipeline.

Sweeps ``(T, Qb, g, grid_order)`` × ``passes`` candidates for a target
shape, prunes guaranteed Mosaic compile failures with the SAME
scoped-VMEM predicate production uses (``footprint_for``/``fit_config``
— a config the runtime would silently shrink is never measured as
written), measures the survivors through ``benchmark.Fixture`` with the
PR-2 ``res.profiler`` cost capture riding along, and writes a
schema-versioned, provenance-stamped ``TUNE_FUSED.json`` that
``fused_config()``/``RAFT_TPU_TUNE_FUSED`` consume.

Every row carries the analytic HBM traffic model
(:func:`raft_tpu.observability.costmodel.fused_traffic_model`) next to
whatever XLA's ``cost_analysis`` measured, so predicted-vs-measured
divergence is part of the artifact — the evidence the grid-order work
is judged by (query-major re-fetches the database ``nq`` times;
database-major streams it once).

Off-TPU the tuner still runs END TO END deterministically: candidates
are ranked by the roofline-perfect time of their modeled traffic
(``min`` over a fixed candidate order — no timing jitter, no RNG), the
table is written with ``measured: false`` provenance, and the loader
treats its ``best_by_passes`` rows exactly like measured ones. That
path is what the tier-1 CPU suite exercises; the first post-tunnel TPU
run replaces the table with measured rows.

CLI::

    python -m raft_tpu.tune.fused                 # tune the driver shape
    python -m raft_tpu.tune.fused --dry           # tiny-shape validation
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import subprocess
import time
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point

# schema 7 (this build): ``pq`` rows may carry a ``pq_mode`` field
# (plain / opq / opq_aniso) — mode-specific schedule picks written by
# :mod:`raft_tpu.tune.ivf` and read by ``ann.ivf_pq.resolve_pq_scan``.
# Schema-6 rows (no pq_mode) load unchanged and match EVERY mode.
# Schema-5 additions (the ``fine_scan`` column) and schema-4 additions
# (db_dtype rows/winners under ``best_by_passes_dtype``) unchanged.
# Committed schema ≤ 5 tables (incl. the measured v5e one) load
# unchanged: no pq column simply means the cost-model crossover
# decides.
TUNE_SCHEMA_VERSION = 7

# counter: tuned-table loads that degraded to built-in defaults, with a
# reason label ("tune.table_degraded" in the metrics docs) — the silent
# half of the degrade-to-defaults contract made loud. Reasons:
# unreadable / invalid / future_schema / row_rejected / shard_mismatch /
# missing (explicit env path only — an absent default table is the
# normal state, not a degradation).
TABLE_DEGRADED = "raft_tpu_tune_table_degraded_total"

_degraded_warned: set = set()


def table_degraded(table: str, reason: str, detail: str = "") -> None:
    """Count one degraded tuned-table load under
    :data:`TABLE_DEGRADED` ``{table, reason}`` and log at WARN once per
    (table, reason) per process — every later occurrence stays counted
    but quiet (a serving loop hitting a stale table must not spam)."""
    try:
        from raft_tpu.observability import get_registry

        reg = get_registry()
        reg.counter(TABLE_DEGRADED, {"table": table, "reason": reason},
                    help="Tuned-table loads degraded to built-in "
                         "defaults, by reason").inc()
        reg.emit({"type": "tune_table_degraded", "table": table,
                  "reason": reason, "detail": detail[:200]})
    except Exception:
        pass
    key = (table, reason)
    if key not in _degraded_warned:
        _degraded_warned.add(key)
        from raft_tpu.core.logger import log_warn

        log_warn("tune table %r degraded to built-ins (%s)%s — this "
                 "WARN fires once per process; the "
                 "tune.table_degraded counter keeps counting", table,
                 reason, f": {detail}" if detail else "")


def _reset_degraded_warnings() -> None:
    """Test hook: re-arm the once-per-process WARN."""
    _degraded_warned.clear()

# the driver benchmark shape (bench.py / BASELINE config 2, one-chip)
DRIVER_SHAPE = (2048, 1_000_000, 128, 64)

_GRID_AXES = {
    "T": (1024, 2048, 4096),
    "Qb": (256, 512, 1024),
    "g": (8, 16, 32),
    "grid_order": ("query", "db", "dbuf"),
    "passes": (1, 3),
    "db_dtype": ("bf16", "int8"),
}


@dataclasses.dataclass(frozen=True)
class Candidate:
    T: int
    Qb: int
    g: int
    passes: int
    grid_order: str = "query"
    db_dtype: str = "bf16"

    def as_row(self) -> Dict:
        return {"T": self.T, "Qb": self.Qb, "g": self.g,
                "passes": self.passes, "grid_order": self.grid_order,
                "db_dtype": self.db_dtype}


def candidate_space(d: int, axes: Optional[Dict] = None
                    ) -> Tuple[List[Candidate], List[Dict]]:
    """(kept, skipped-rows) for the sweep. Pruning is the production
    predicate chain — ``_valid_cfg`` then ``fit_config`` unshrunk at
    feature width ``d`` — so nothing the runtime would reject or
    silently reshape is ever measured; each skip is recorded with its
    reason (no silent truncation of the sweep)."""
    from raft_tpu.distance.knn_fused import (_D_SINGLE_SHOT, _valid_cfg,
                                             fit_config)

    axes = dict(_GRID_AXES, **(axes or {}))
    kept: List[Candidate] = []
    skipped: List[Dict] = []
    for T, Qb, g, order, p, dt in itertools.product(
            axes["T"], axes["Qb"], axes["g"], axes["grid_order"],
            axes["passes"], axes.get("db_dtype", ("bf16",))):
        cand = Candidate(T, Qb, g, p, order, dt)
        if not _valid_cfg(T, Qb, g, order):
            skipped.append(dict(cand.as_row(), skipped="invalid_cfg"))
            continue
        if dt == "int8" and (order == "query" or d > _D_SINGLE_SHOT):
            # the quantized kernels are packed database-major
            # single-shot only — prepare would downgrade the dtype, so
            # the point would silently measure bf16
            skipped.append(dict(cand.as_row(), skipped="q8_envelope"))
            continue
        if fit_config(T, Qb, d, p, g, order, dt) != (T, Qb):
            # over the scoped-VMEM budget: a guaranteed Mosaic compile
            # failure (or a silent shrink to a point already swept)
            skipped.append(dict(cand.as_row(),
                                skipped="vmem_footprint"))
            continue
        kept.append(cand)
    return kept, skipped


def _git_commit(repo: Optional[str] = None) -> str:
    from raft_tpu.native import _REPO_ROOT

    repo = repo or _REPO_ROOT
    try:
        r = subprocess.run(["git", "-C", repo, "rev-parse", "--short",
                            "HEAD"], capture_output=True, text=True,
                           timeout=10)
        head = r.stdout.strip() or "unknown"
        s = subprocess.run(["git", "-C", repo, "status", "--porcelain"],
                           capture_output=True, text=True, timeout=10)
        return head + "-dirty" if s.stdout.strip() else head
    except Exception:
        return "unknown"


def provenance(measured: bool) -> Dict:
    """Who/where/when a tune table came from — logged by the loader so
    a table measured on one chip generation (or never measured at all)
    can't masquerade as evidence for another."""
    import jax

    from raft_tpu.utils.arch import chip_spec, device_kind

    return {
        "chip": chip_spec().name,
        "device_kind": device_kind(),
        "platform": jax.default_backend(),
        "git_commit": _git_commit(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "measured": bool(measured),
        "schema": TUNE_SCHEMA_VERSION,
    }


def validate_tune_table(tbl) -> List[str]:
    """Structural validation shared by the writer (self-check before
    anything lands on disk) and the ``fused_config`` loader (a corrupt
    table degrades to built-ins instead of crashing knn). Legacy tables
    (no schema/provenance) validate clean — only structural corruption
    is an error; semantic per-row checks (``_valid_cfg``/``fit_config``)
    happen at load."""
    errors: List[str] = []
    if not isinstance(tbl, dict):
        return ["table is not a JSON object"]
    if "schema" in tbl and not isinstance(tbl["schema"], int):
        errors.append("schema is not an integer")
    if "provenance" in tbl and not isinstance(tbl["provenance"], dict):
        errors.append("provenance is not an object")
    shape = tbl.get("shape")
    if shape is not None and not (
            isinstance(shape, (list, tuple)) and len(shape) >= 4
            and all(isinstance(v, (int, float)) for v in shape)):
        errors.append("shape is not a [nq, m, d, k] list")
    rows = tbl.get("rows", [])
    if not isinstance(rows, list):
        errors.append("rows is not a list")
        rows = []
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errors.append(f"rows[{i}] is not an object")
            continue
        if "seconds" in row or "predicted_seconds" in row:
            for key in ("T", "Qb", "g"):
                if not isinstance(row.get(key), int):
                    errors.append(f"rows[{i}].{key} missing/non-int")
    fs = tbl.get("fine_scan")
    if fs is not None:
        if not isinstance(fs, list):
            errors.append("fine_scan is not a list")
        else:
            for i, row in enumerate(fs):
                if not (isinstance(row, dict)
                        and isinstance(row.get("n_lists"), int)
                        and isinstance(row.get("n_probes"), int)
                        and row.get("fine_scan") in ("query", "list")):
                    errors.append(f"fine_scan[{i}] malformed")
    pq = tbl.get("pq")
    if pq is not None:
        if not isinstance(pq, list):
            errors.append("pq is not a list")
        else:
            for i, row in enumerate(pq):
                if not (isinstance(row, dict)
                        and isinstance(row.get("n_lists"), int)
                        and isinstance(row.get("n_probes"), int)
                        and isinstance(row.get("pq_bits"), int)
                        and row.get("pq_scan") in ("pq", "flat")):
                    errors.append(f"pq[{i}] malformed")
    for key in ("best", "best_by_passes", "best_by_passes_dtype"):
        entry = tbl.get(key)
        if entry is None:
            continue
        entries = (entry.values()
                   if key in ("best_by_passes", "best_by_passes_dtype")
                   and isinstance(entry, dict) else [entry])
        for e in entries:
            if not isinstance(e, dict) or not all(
                    isinstance(e.get(f), int) for f in ("T", "Qb", "g")):
                errors.append(f"{key} entry malformed")
    return errors


def target_spec():
    """The roofline the deterministic fallback ranks against: the host
    chip when it IS a TPU, else the last-measured driver chip (v5e —
    every BENCH_r* TPU round so far). Ranking against the host CPU's
    synthetic roofline would classify every candidate compute-bound and
    tie out exactly the y-traffic differences this tuner exists to
    rank."""
    import jax

    from raft_tpu.utils.arch import TPU_SPECS, chip_spec

    if jax.default_backend() == "tpu":
        return chip_spec()
    return TPU_SPECS[(5, "e")]


def predicted_row(shape: Sequence[int], cand: Candidate,
                  spec=None) -> Dict:
    """Deterministic (model-only) evidence for one candidate: the
    analytic traffic model placed on the target chip's roofline. The
    prediction key is ``predicted_seconds`` = roofline-perfect time —
    honest naming; it is never written as ``seconds``."""
    from raft_tpu.observability import costmodel

    spec = spec if spec is not None else target_spec()
    nq, m, d, k = (int(v) for v in shape[:4])
    model = costmodel.fused_traffic_model(
        nq, m, d, k, cand.T, cand.Qb, cand.g, cand.passes,
        cand.grid_order, cand.db_dtype)
    rec = costmodel.fused_traffic_record(
        nq, m, d, k, cand.T, cand.Qb, cand.g, cand.passes,
        cand.grid_order, cand.db_dtype)
    est = costmodel.roofline(rec, spec)
    row = cand.as_row()
    row.update({
        "predicted_seconds": est.roof_seconds,
        "predicted_gbps": (nq * m * 4.0 / est.roof_seconds / 1e9
                           if est.roof_seconds else None),
        "model_total_bytes": model["total_bytes"],
        "model_y_bytes": model["y_bytes"],
        "model_y_stream_factor": model["y_stream_factor"],
        "bound": est.bound,
    })
    return row


@instrument("tune.autotune_fused")
def autotune_fused(res=None, shape: Sequence[int] = DRIVER_SHAPE,
                   out_path: Optional[str] = "TUNE_FUSED.json",
                   budget_s: float = 2400.0,
                   measure: Optional[bool] = None,
                   reps: int = 3, axes: Optional[Dict] = None,
                   data=None) -> Dict:
    """Tune the fused pipeline for ``shape`` = (nq, m, d, k).

    ``measure=None`` auto-selects: real timing on TPU, the
    deterministic model-ranked fallback elsewhere. Measured mode builds
    the index ONCE per candidate (steady-state query throughput, the
    bench.py metric), times through ``benchmark.Fixture`` (cost capture
    + roofline fields ride along via ``res.profiler``), honors the
    ``budget_s`` deadline between points, and writes incrementally so a
    killed sweep loses one point. Returns the table (also written to
    ``out_path`` unless None)."""
    import jax

    from raft_tpu.core.resources import ensure_resources
    from raft_tpu.observability import costmodel

    fault_point("autotune_fused")
    res = ensure_resources(res)
    nq, m, d, k = (int(v) for v in shape[:4])
    if measure is None:
        measure = jax.default_backend() == "tpu"
    cands, skipped = candidate_space(d, axes)
    rows: List[Dict] = list(skipped)

    def _winners(ranked, key):
        """(best_by_passes — bf16 rows under bare-passes keys, the
        schema-3 contract old loaders read — and best_by_passes_dtype,
        winners per (passes, db_dtype) under 'p:dtype' keys)."""
        by_p: Dict[str, Dict] = {}
        by_pd: Dict[str, Dict] = {}
        for p in sorted({c.passes for c in cands}):
            bp = [r for r in ranked if r["passes"] == p
                  and r.get("db_dtype", "bf16") == "bf16"]
            if bp:
                by_p[str(p)] = min(bp, key=key)
            for dt in sorted({c.db_dtype for c in cands}):
                rp = [r for r in ranked if r["passes"] == p
                      and r.get("db_dtype", "bf16") == dt]
                if rp:
                    by_pd[f"{p}:{dt}"] = min(rp, key=key)
        return by_p, by_pd

    def _flush(best, best_by_passes, best_by_dtype=None):
        prov = provenance(measured=measure)
        if not measure:
            prov["target_chip"] = target_spec().name
        tbl = {
            "schema": TUNE_SCHEMA_VERSION,
            "provenance": prov,
            "shape": [nq, m, d, k],
            "rows": rows,
            "best": best,
            "best_by_passes": best_by_passes,
            "best_by_passes_dtype": best_by_dtype or {},
        }
        errors = validate_tune_table(tbl)
        if errors:     # writer self-check: never ship a corrupt table
            raise ValueError(f"autotune_fused produced an invalid "
                             f"table: {errors}")
        if out_path:
            with open(out_path, "w") as f:
                json.dump(tbl, f, indent=1)
                f.write("\n")
        return tbl

    if not measure:
        # deterministic fallback: rank every candidate by the modeled
        # roofline-perfect time on the TARGET chip's roofline; fixed
        # iteration order, no RNG/clock
        spec = target_spec()
        rows.extend(predicted_row(shape, c, spec) for c in cands)
        ranked = [r for r in rows if "predicted_seconds" in r]
        best = min(ranked, key=lambda r: r["predicted_seconds"],
                   default=None)
        by_p, by_pd = _winners(ranked,
                               lambda r: r["predicted_seconds"])
        return _flush(best, by_p, by_pd)

    from raft_tpu.benchmark import Fixture
    from raft_tpu.distance.knn_fused import knn_fused, prepare_knn_index
    from raft_tpu.random import RngState, make_blobs

    if data is None:
        X, _ = make_blobs(res, RngState(0), m, d, n_clusters=64,
                          cluster_std=2.0)
    else:
        X = data
    Q = X[:nq]
    jax.block_until_ready(X)
    fx = Fixture(res=res, reps=reps)
    eff_bytes = nq * m * 4.0
    deadline = time.monotonic() + budget_s
    best = None
    best_by: Dict[str, Dict] = {}
    best_by_dt: Dict[str, Dict] = {}
    for cand in cands:
        if time.monotonic() > deadline:
            rows.append({"budget_expired_after":
                         len([r for r in rows if "seconds" in r])})
            break
        row = cand.as_row()
        row.update({f"model_{key}": v for key, v in
                    costmodel.fused_traffic_model(
                        nq, m, d, k, cand.T, cand.Qb, cand.g,
                        cand.passes, cand.grid_order,
                        cand.db_dtype).items()
                    if key not in ("grid_order", "db_dtype")})
        try:
            idx = prepare_knn_index(
                X, passes=cand.passes, T=cand.T, Qb=cand.Qb, g=cand.g,
                grid_order=cand.grid_order, db_dtype=cand.db_dtype)
            name = (f"tune_fused[T={cand.T},Qb={cand.Qb},g={cand.g},"
                    f"{cand.grid_order},p{cand.passes},"
                    f"{cand.db_dtype}]")
            r = fx.run(lambda q: knn_fused(q, idx, k=k)[0], Q,
                       name=name)
            row["seconds"] = round(r["seconds"], 5)
            row["gbps"] = round(eff_bytes / r["seconds"] / 1e9, 1)
            # PR-2 evidence fields (XLA cost capture via res.profiler)
            for f in ("bytes_accessed", "flops", "roofline_frac",
                      "bound"):
                if f in r:
                    row[f] = r[f]
            # one explicit capture of the winner-so-far's executable so
            # the tune artifact has a cost record even when Fixture's
            # tracing was disabled mid-sweep
            res.profiler.capture_fn(name, lambda q: knn_fused(
                q, idx, k=k)[0], Q)
        except Exception as e:   # point off-envelope / lowering failure
            row["error"] = f"{type(e).__name__}: {e}"[:200]
        rows.append(row)
        ok = [r for r in rows if "seconds" in r]
        best = min(ok, key=lambda r: r["seconds"]) if ok else None
        best_by, best_by_dt = _winners(ok, lambda r: r["seconds"])
        _flush(best, best_by, best_by_dt)  # incremental: a kill loses
        #                                    one point
    return _flush(best, best_by, best_by_dt)


# kept as a module-level alias so callers can write tables produced
# elsewhere (tests, merge tooling) through the same self-check
def write_tune_table(path: str, tbl: Dict) -> None:
    errors = validate_tune_table(tbl)
    if errors:
        raise ValueError(f"write_tune_table: invalid table: {errors}")
    with open(path, "w") as f:
        json.dump(tbl, f, indent=1)
        f.write("\n")


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--shape", type=int, nargs=4,
                   default=list(DRIVER_SHAPE),
                   metavar=("NQ", "M", "D", "K"))
    p.add_argument("--out", default="TUNE_FUSED.json")
    p.add_argument("--budget-s", type=float, default=float(
        os.environ.get("TUNE_FUSED_BUDGET_S", "2400")))
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--dry", action="store_true",
                   help="tiny-shape harness validation (no artifact)")
    p.add_argument("--predict-only", action="store_true",
                   help="force the deterministic model-ranked fallback")
    args = p.parse_args(argv)
    shape = ((256, 20_000, 64, 32) if args.dry
             else tuple(args.shape))
    tbl = autotune_fused(
        shape=shape,
        out_path=None if args.dry else args.out,
        budget_s=args.budget_s,
        measure=False if args.predict_only else None,
        reps=1 if args.dry else args.reps)
    best = tbl.get("best")
    print(json.dumps({"best": best,
                      "rows": len(tbl.get("rows", [])),
                      "measured": tbl["provenance"]["measured"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
