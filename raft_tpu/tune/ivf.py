"""IVF fine-scan + PQ schedule autotuner — the ``fine_scan``
(schema 5) and ``pq`` (schema 6) columns of the tune table.

``autotune_fine_scan`` sweeps ``(n_lists, n_probes)`` geometries for
an index shape and records, per point, the modeled bytes of BOTH
fine-scan schedules (query-major gather vs list-major stream —
:func:`raft_tpu.observability.costmodel.ivf_traffic_model` on the
actual list-size histogram when one is provided) and the winning
schedule. Off-TPU the sweep is the deterministic model ranking
(``measured: false``), exactly like :mod:`raft_tpu.tune.fused`'s
fallback; a TPU round replaces the modeled winners with measured ones
by timing both schedules through ``search_ivf_flat(fine_scan=...)``.

The rows land under the tune table's top-level ``fine_scan`` key
(TUNE_FUSED.json, schema 5 — schema ≤ 4 tables simply have no such
column and every reader falls back to the cost-model crossover).
``fine_scan_config`` is the loader ``ann.ivf_flat.resolve_fine_scan``
consults: corrupt/absent/mismatched tables degrade to ``None`` (cost
model decides) with the shared ``table_degraded`` counter.

``autotune_pq_scan`` / ``pq_scan_config`` are the IVF-PQ siblings
(top-level ``pq`` key, rows keyed (n_lists, n_probes, pq_bits[,
pq_mode]) → "pq" | "flat"): same deterministic model ranking, same
degrade-to-crossover loader contract, same committed-table
back-compat — a schema ≤ 5 table simply has no ``pq`` column and
``ann.ivf_pq.resolve_pq_scan`` falls to ``costmodel.choose_pq_scan``.
Schema 7 adds the optional per-row ``pq_mode`` column (plain / opq /
opq_aniso — quantizer modes change the rerun economics, so their
tuned picks differ); schema-6 rows carry no ``pq_mode`` and match
every mode, so older committed tables load unchanged.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point

_SCHEDULES = ("query", "list")
_PQ_SCHEDULES = ("pq", "flat")

# loader cache: path → (mtime, {(n_lists, n_probes): schedule})
_cache: Dict[str, tuple] = {}
# pq loader cache: path → (mtime, {(n_lists, n_probes, pq_bits): sched})
_pq_cache: Dict[str, tuple] = {}


def fine_scan_rows(shape: Sequence[int], lists: Sequence[int],
                   list_sizes=None, padded_sizes=None,
                   db_dtype: str = "f32") -> List[Dict]:
    """The deterministic (model-ranked) sweep: one row per
    (n_lists, n_probes) point with both schedules' modeled fine-scan
    bytes and the crossover pick."""
    from raft_tpu.observability.costmodel import (choose_fine_scan,
                                                  ivf_traffic_model)

    nq, m, d, k = (int(v) for v in shape[:4])
    rows: List[Dict] = []
    for L in lists:
        L = int(L)
        probe_window = max(8, -(-m // max(L, 1) // 8) * 8)
        slab_rows = probe_window * L
        p = 1
        probe_pts = []
        while p < L:
            probe_pts.append(p)
            p *= 2
        for P in probe_pts:
            model = ivf_traffic_model(
                nq, m, d, k, L, P, probe_window, slab_rows,
                db_dtype=db_dtype, list_sizes=list_sizes,
                padded_sizes=padded_sizes)
            rows.append({
                "n_lists": L,
                "n_probes": P,
                "db_dtype": db_dtype,
                "fine_scan": choose_fine_scan(model),
                "model_stream_bytes": model["fine_stream_bytes"],
                "model_gather_bytes": model["fine_gather_bytes"],
                "gather_overread": round(model["gather_overread"], 3),
            })
    return rows


@instrument("tune.autotune_fine_scan")
def autotune_fine_scan(shape: Sequence[int],
                       lists: Sequence[int] = (1024,),
                       list_sizes=None, padded_sizes=None,
                       db_dtype: str = "f32") -> List[Dict]:
    """Produce the ``fine_scan`` rows for a tune table. Deterministic
    (model-ranked) everywhere today — the modeled crossover IS the
    chooser's production logic; a measured TPU round appends
    ``seconds_query``/``seconds_list`` per row and flips ``fine_scan``
    to the measured winner (the loader treats both alike)."""
    fault_point("autotune_fine_scan")
    return fine_scan_rows(shape, lists, list_sizes, padded_sizes,
                          db_dtype)


def _load_rows(path: str) -> Optional[Dict]:
    """{(n_lists, n_probes): schedule} from a table's ``fine_scan``
    rows, or None when the table has none / is unreadable (counted
    through the shared degrade path when it LOOKS like a table but
    cannot be used)."""
    from raft_tpu.tune.fused import table_degraded

    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    cached = _cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    try:
        with open(path) as f:
            tbl = json.load(f)
    except (OSError, ValueError) as e:
        table_degraded("fine_scan", "unreadable", str(e)[:120])
        return None
    rows = tbl.get("fine_scan") if isinstance(tbl, dict) else None
    out: Dict = {}
    if isinstance(rows, list):
        for row in rows:
            if not isinstance(row, dict):
                table_degraded("fine_scan", "row_rejected",
                               "non-object row")
                continue
            sched = row.get("fine_scan")
            L, P = row.get("n_lists"), row.get("n_probes")
            if sched in _SCHEDULES and isinstance(L, int) \
                    and isinstance(P, int):
                out[(L, P)] = sched
            else:
                table_degraded("fine_scan", "row_rejected",
                               f"bad row {row}"[:120])
    _cache[path] = (mtime, out)
    return out


def fine_scan_config(n_lists: int, n_probes: int) -> Optional[str]:
    """The tuned fine-scan schedule for an exact (n_lists, n_probes)
    geometry, or None (caller falls to the cost-model crossover).
    Reads the same table ``fused_config`` does — the committed
    ``TUNE_FUSED.json`` or the ``RAFT_TPU_TUNE_FUSED`` override."""
    from raft_tpu.core import env
    from raft_tpu.native import _REPO_ROOT

    path = env.raw("RAFT_TPU_TUNE_FUSED") or os.path.join(
        _REPO_ROOT, "TUNE_FUSED.json")
    rows = _load_rows(path)
    if not rows:
        return None
    return rows.get((int(n_lists), int(n_probes)))


# ----------------------------------------------------- the pq column
def pq_rows(shape: Sequence[int], lists: Sequence[int],
            pq_dim: int, pq_bits: Sequence[int] = (4, 8),
            list_sizes=None, padded_sizes=None,
            pq_mode: str = "plain") -> List[Dict]:
    """The deterministic (model-ranked) PQ sweep: one row per
    (n_lists, n_probes, pq_bits) point at quantizer mode ``pq_mode``
    with the ADC and best-flat schedules' modeled bytes and the
    crossover pick."""
    from raft_tpu.observability.costmodel import (choose_pq_scan,
                                                  ivf_traffic_model)

    nq, m, d, k = (int(v) for v in shape[:4])
    rows: List[Dict] = []
    for L in lists:
        L = int(L)
        probe_window = max(8, -(-m // max(L, 1) // 8) * 8)
        slab_rows = probe_window * L
        p = 1
        probe_pts = []
        while p < L:
            probe_pts.append(p)
            p *= 2
        for P in probe_pts:
            for bits in pq_bits:
                model = ivf_traffic_model(
                    nq, m, d, k, L, P, probe_window, slab_rows,
                    list_sizes=list_sizes, padded_sizes=padded_sizes,
                    pq_dim=int(pq_dim), pq_bits=int(bits))
                rows.append({
                    "n_lists": L,
                    "n_probes": P,
                    "pq_dim": int(pq_dim),
                    "pq_bits": int(bits),
                    "pq_mode": str(pq_mode),
                    "pq_scan": choose_pq_scan(model),
                    "model_pq_bytes": model["pq_stream_bytes"],
                    "model_flat_bytes": min(
                        model["fine_stream_bytes"],
                        model["fine_gather_bytes"]),
                    "pq_bytes_ratio": round(
                        model["pq_bytes_ratio"], 5),
                })
    return rows


@instrument("tune.autotune_pq_scan")
def autotune_pq_scan(shape: Sequence[int], lists: Sequence[int] = (1024,),
                     pq_dim: Optional[int] = None,
                     pq_bits: Sequence[int] = (4, 8),
                     list_sizes=None, padded_sizes=None,
                     pq_mode: str = "plain") -> List[Dict]:
    """Produce the ``pq`` rows for a schema-7 tune table. Deterministic
    (model-ranked) everywhere today, exactly like
    :func:`autotune_fine_scan` (whose tuner fault site this sweep
    shares — one schedule-tuner seam); a measured TPU round appends
    ``seconds_pq``/``seconds_flat`` per row and flips ``pq_scan`` to
    the measured winner."""
    fault_point("autotune_fine_scan")
    d = int(shape[2])
    if pq_dim is None:
        pq_dim = max(1, d // 4)
        while d % pq_dim:
            pq_dim -= 1
    return pq_rows(shape, lists, pq_dim, pq_bits, list_sizes,
                   padded_sizes, pq_mode=pq_mode)


def _load_pq_rows(path: str) -> Optional[Dict]:
    """{(n_lists, n_probes, pq_bits, pq_mode_or_None): schedule} from a
    table's ``pq`` rows — the :func:`_load_rows` contract for the
    schema-7 column. A row without ``pq_mode`` (schema ≤ 6) keys with
    None and matches every quantizer mode."""
    from raft_tpu.tune.fused import table_degraded

    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    cached = _pq_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    try:
        with open(path) as f:
            tbl = json.load(f)
    except (OSError, ValueError) as e:
        table_degraded("pq", "unreadable", str(e)[:120])
        return None
    rows = tbl.get("pq") if isinstance(tbl, dict) else None
    out: Dict = {}
    if isinstance(rows, list):
        for row in rows:
            if not isinstance(row, dict):
                table_degraded("pq", "row_rejected", "non-object row")
                continue
            sched = row.get("pq_scan")
            L, P = row.get("n_lists"), row.get("n_probes")
            bits = row.get("pq_bits")
            mode = row.get("pq_mode")
            mode_ok = mode is None or isinstance(mode, str)
            if sched in _PQ_SCHEDULES and isinstance(L, int) \
                    and isinstance(P, int) and isinstance(bits, int) \
                    and mode_ok:
                out[(L, P, bits, mode)] = sched
            else:
                table_degraded("pq", "row_rejected",
                               f"bad row {row}"[:120])
    _pq_cache[path] = (mtime, out)
    return out


def pq_scan_config(n_lists: int, n_probes: int, pq_bits: int,
                   pq_mode: str = "plain") -> Optional[str]:
    """The tuned PQ schedule for an exact (n_lists, n_probes, pq_bits)
    geometry at quantizer mode ``pq_mode``, or None (caller falls to
    the cost-model crossover). A mode-specific (schema 7) row wins;
    otherwise a mode-less (schema ≤ 6) row matches any mode — older
    committed tables keep working. Reads the same table
    ``fused_config`` does; schema ≤ 5 tables have no ``pq`` column and
    return None — the committed-table back-compat contract."""
    from raft_tpu.core import env
    from raft_tpu.native import _REPO_ROOT

    path = env.raw("RAFT_TPU_TUNE_FUSED") or os.path.join(
        _REPO_ROOT, "TUNE_FUSED.json")
    rows = _load_pq_rows(path)
    if not rows:
        return None
    key = (int(n_lists), int(n_probes), int(pq_bits))
    hit = rows.get(key + (str(pq_mode),))
    if hit is not None:
        return hit
    return rows.get(key + (None,))
