"""Evidence-driven autotuning (the fitted-heuristic role of the
reference's ``cpp/scripts/heuristics/select_k`` fitting pipeline,
rebuilt on the PR-2 roofline evidence chain: candidates are pruned by
the scoped-VMEM footprint model, measured through ``benchmark.Fixture``
+ ``res.profiler`` cost capture, and the winner ships as a
schema-validated, provenance-stamped table the runtime defaults
consume)."""

from raft_tpu.tune.fused import (TUNE_SCHEMA_VERSION, autotune_fused,
                                 candidate_space, validate_tune_table,
                                 write_tune_table)
from raft_tpu.tune.ivf import (autotune_fine_scan, fine_scan_config,
                               fine_scan_rows)
from raft_tpu.tune.sharded import (autotune_sharded, sharded_config,
                                   sharded_candidate_space,
                                   sharded_time_model)

__all__ = [
    "TUNE_SCHEMA_VERSION",
    "autotune_fine_scan",
    "autotune_fused",
    "autotune_sharded",
    "fine_scan_config",
    "fine_scan_rows",
    "candidate_space",
    "sharded_candidate_space",
    "sharded_config",
    "sharded_time_model",
    "validate_tune_table",
    "write_tune_table",
]
