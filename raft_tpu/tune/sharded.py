"""Autotuner for the SHARDED fused-KNN pipeline (ISSUE 4).

Sweeps ``(merge strategy, micro-batch count, local T/Qb) × passes`` for
a target shape at a given shard count, pruning with the SAME predicates
production uses — ``_valid_cfg`` + ``fit_config`` unshrunk (a local
config the runtime would silently reshape is never measured as
written), and the power-of-two constraint of the tournament merge —
and writes a schema-3, provenance-stamped ``TUNE_SHARDED.json``
(:func:`raft_tpu.tune.fused.provenance` / ``validate_tune_table`` are
reused verbatim, so one loader hardening covers both tables).

Off-TPU the tuner runs END TO END deterministically, like
``autotune_fused``: every candidate is ranked by a modeled pipeline
time on the target chip —

    local   = roofline-perfect time of the PER-SHARD fused kernel
              (``costmodel.fused_traffic_record`` on the nq × m/p × d
              shard shape)
    merge   = ``costmodel.ici_time_model`` per query block ×
              micro-batches
    total   = block-pipelined: the first block's local compute, then
              nb−1 overlapped stages of max(local_block, merge_block),
              then the last merge (the double-buffered schedule
              knn_fused_sharded is shaped for)

— fixed candidate order, no RNG, no clock; ``measured: false``
provenance. The first post-tunnel TPU round replaces the table with
measured rows.

CLI::

    python -m raft_tpu.tune.sharded                # north-star shape
    python -m raft_tpu.tune.sharded --dry          # tiny-shape check
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point
from raft_tpu.tune.fused import (TUNE_SCHEMA_VERSION, provenance,
                                 table_degraded, validate_tune_table,
                                 write_tune_table)

# the north-star workload (BENCH_NORTHSTAR.json) — the shape that is at
# the one-chip capacity wall and exists to be sharded
NORTHSTAR_SHAPE = (2048, 10_000_000, 256, 64)

_SHARDED_AXES = {
    "T": (512, 1024, 2048),
    "Qb": (256, 512),
    "g": (2, 4, 8),
    "merge": ("allgather", "tournament"),
    "micro_batches": (1, 2, 4, 8),
    "passes": (1, 3),
    "db_dtype": ("bf16", "int8"),
}

# the sharded sweep tunes the stream-once local kernel — the db-major
# order IS the tentpole configuration (dbuf/query remain reachable via
# knn_fused_sharded's grid_order kwarg, tuned by the fused sweep)
_GRID_ORDER = "db"


@dataclasses.dataclass(frozen=True)
class ShardedCandidate:
    T: int
    Qb: int
    g: int
    merge: str
    micro_batches: int
    passes: int
    db_dtype: str = "bf16"

    def as_row(self) -> Dict:
        return {"T": self.T, "Qb": self.Qb, "g": self.g,
                "merge": self.merge,
                "micro_batches": self.micro_batches,
                "passes": self.passes, "grid_order": _GRID_ORDER,
                "db_dtype": self.db_dtype}


def sharded_candidate_space(d: int, p: int, axes: Optional[Dict] = None
                            ) -> Tuple[List[ShardedCandidate],
                                       List[Dict]]:
    """(kept, skipped-rows) for the sharded sweep. The pruning chain is
    production's: ``_valid_cfg`` → ``fit_config`` unshrunk at feature
    width ``d`` → the tournament power-of-two constraint; each skip is
    recorded with its reason (no silent sweep truncation). ``g`` is
    swept too: the stream-once db order holds a whole [g·T, d] group
    VMEM-resident, so the single-chip tuned g can be a guaranteed
    scoped-VMEM reject at the sharded d."""
    from raft_tpu.distance.knn_fused import (_D_SINGLE_SHOT, _valid_cfg,
                                             fit_config)

    axes = dict(_SHARDED_AXES, **(axes or {}))
    kept: List[ShardedCandidate] = []
    skipped: List[Dict] = []
    pow2 = p > 0 and not (p & (p - 1))
    for T, Qb, g, merge, nb, passes, dt in itertools.product(
            axes["T"], axes["Qb"], axes["g"], axes["merge"],
            axes["micro_batches"], axes["passes"],
            axes.get("db_dtype", ("bf16",))):
        cand = ShardedCandidate(T, Qb, g, merge, nb, passes, dt)
        if not _valid_cfg(T, Qb, g, _GRID_ORDER):
            skipped.append(dict(cand.as_row(), skipped="invalid_cfg"))
            continue
        if dt == "int8" and d > _D_SINGLE_SHOT:
            skipped.append(dict(cand.as_row(), skipped="q8_envelope"))
            continue
        if fit_config(T, Qb, d, passes, g, _GRID_ORDER,
                      dt) != (T, Qb):
            skipped.append(dict(cand.as_row(),
                                skipped="vmem_footprint"))
            continue
        if merge == "tournament" and not pow2:
            skipped.append(dict(cand.as_row(), skipped="merge_pow2"))
            continue
        kept.append(cand)
    return kept, skipped


def sharded_time_model(shape: Sequence[int], p: int,
                       cand: ShardedCandidate, spec=None) -> Dict:
    """Modeled end-to-end time of one sharded candidate (see module
    doc): per-shard local roofline time + overlapped per-block merge.
    Deterministic — the off-TPU ranking key AND the modeled half of
    every measured row."""
    from raft_tpu.observability import costmodel
    from raft_tpu.tune.fused import target_spec

    spec = spec if spec is not None else target_spec()
    nq, m, d, k = (int(v) for v in shape[:4])
    m_loc = -(-m // max(p, 1))
    rec = costmodel.fused_traffic_record(
        nq, m_loc, d, k, cand.T, cand.Qb, cand.g, cand.passes,
        _GRID_ORDER, cand.db_dtype)
    local_s = costmodel.roofline(rec, spec).roof_seconds
    nb = max(1, cand.micro_batches)
    nq_b = -(-nq // nb)
    ici = costmodel.ici_time_model(p, nq_b, k, cand.merge, spec)
    merge_b = ici["merge_seconds"]
    local_b = local_s / nb
    # block pipeline: fill (one local block), nb−1 overlapped stages,
    # drain (the last merge)
    total = local_b + (nb - 1) * max(local_b, merge_b) + merge_b
    return {
        "predicted_seconds": total,
        "model_local_seconds": local_s,
        "model_merge_seconds": nb * merge_b,
        "model_ici_bytes_per_device": nb * ici["wire_bytes_per_device"],
        "model_ici_rounds": nb * ici["rounds"],
        "model_busbw_frac": ((nb * ici["wire_bytes_per_device"])
                             / ((spec.ici_bw or spec.hbm_bw) * total)
                             if total else 0.0),
        "model_local_bytes": rec.bytes_accessed,
    }


def predicted_sharded_row(shape: Sequence[int], p: int,
                          cand: ShardedCandidate, spec=None) -> Dict:
    nq, m, _, _ = (int(v) for v in shape[:4])
    row = cand.as_row()
    row.update(sharded_time_model(shape, p, cand, spec))
    t = row["predicted_seconds"]
    row["predicted_gbps"] = nq * m * 4.0 / t / 1e9 if t else None
    return row


_TUNED_SHARDED = ...    # lazy: parsed table dict, or None


def sharded_config(p: Optional[int] = None) -> Dict:
    """Best tuned (merge, micro_batches, T, Qb) row from
    ``TUNE_SHARDED.json`` (``RAFT_TPU_TUNE_SHARDED`` overrides the
    path), or {} when no table exists, the table is corrupt, or it was
    tuned for a different shard count — the same degrade-to-defaults
    contract as ``fused_config``."""
    global _TUNED_SHARDED
    if _TUNED_SHARDED is ...:
        _TUNED_SHARDED = _load_sharded_table()
    tbl = _TUNED_SHARDED
    if not tbl:
        return {}
    if p is not None and tbl.get("n_shards") not in (None, int(p)):
        table_degraded("sharded", "shard_mismatch",
                       f"table tuned for p={tbl.get('n_shards')}, "
                       f"call wants p={p}")
        return {}
    best = tbl.get("best")
    return dict(best) if isinstance(best, dict) else {}


def _load_sharded_table() -> Optional[Dict]:
    from raft_tpu.core.logger import log_info
    from raft_tpu.native import _REPO_ROOT

    path_env = os.environ.get("RAFT_TPU_TUNE_SHARDED")
    path = path_env or os.path.join(_REPO_ROOT, "TUNE_SHARDED.json")
    if fault_point("tune_table_read") == "corrupt":
        table_degraded("sharded", "unreadable",
                       f"{path}: injected corrupt table read")
        return None
    try:
        with open(path) as f:
            tbl = json.load(f)
    except FileNotFoundError:
        if path_env:
            table_degraded("sharded", "missing", path)
        return None
    except Exception as e:
        table_degraded("sharded", "unreadable",
                       f"{path}: {type(e).__name__}: {e}")
        return None
    errors = validate_tune_table(tbl)
    if errors:
        table_degraded("sharded", "invalid",
                       f"{path}: " + "; ".join(errors))
        return None
    if int(tbl.get("schema", 1)) > TUNE_SCHEMA_VERSION:
        table_degraded("sharded", "future_schema",
                       f"{path}: schema {tbl.get('schema')}")
        return None
    prov = tbl.get("provenance", {})
    log_info("sharded_config: loaded %s (schema %s, chip=%s, "
             "measured=%s)", path, tbl.get("schema", "legacy"),
             prov.get("chip", "unknown"),
             prov.get("measured", "unknown"))
    return tbl


@instrument("tune.autotune_sharded")
def autotune_sharded(res=None, shape: Sequence[int] = NORTHSTAR_SHAPE,
                     p: Optional[int] = None,
                     out_path: Optional[str] = "TUNE_SHARDED.json",
                     budget_s: float = 2400.0,
                     measure: Optional[bool] = None,
                     reps: int = 3, axes: Optional[Dict] = None,
                     mesh=None, data=None) -> Dict:
    """Tune the sharded pipeline for ``shape`` = (nq, m, d, k) over
    ``p`` shards (default: every local device).

    ``measure=None`` auto-selects: real timing on a multi-device TPU
    backend, the deterministic model-ranked fallback elsewhere.
    Measured mode prepares the sharded index once per (T, Qb, passes)
    local config (steady-state query throughput), times
    ``knn_fused_sharded`` through ``benchmark.Fixture`` with the
    ``res.profiler`` cost capture riding along, honors ``budget_s``,
    and writes incrementally. Every row carries the deterministic
    :func:`sharded_time_model` fields next to whatever was measured,
    so predicted-vs-measured divergence is part of the artifact."""
    import jax

    from raft_tpu.core.resources import ensure_resources

    fault_point("autotune_sharded")
    res = ensure_resources(res)
    nq, m, d, k = (int(v) for v in shape[:4])
    if p is None:
        p = len(jax.devices())
    if measure is None:
        measure = jax.default_backend() == "tpu" and p > 1
    cands, skipped = sharded_candidate_space(d, p, axes)
    rows: List[Dict] = list(skipped)

    def _winners(ranked, key):
        by_p: Dict[str, Dict] = {}
        by_pd: Dict[str, Dict] = {}
        for ps in sorted({c.passes for c in cands}):
            bp = [r for r in ranked if r["passes"] == ps
                  and r.get("db_dtype", "bf16") == "bf16"]
            if bp:
                by_p[str(ps)] = min(bp, key=key)
            for dt in sorted({c.db_dtype for c in cands}):
                rp = [r for r in ranked if r["passes"] == ps
                      and r.get("db_dtype", "bf16") == dt]
                if rp:
                    by_pd[f"{ps}:{dt}"] = min(rp, key=key)
        return by_p, by_pd

    def _flush(best, best_by_passes, best_by_dtype=None):
        prov = provenance(measured=measure)
        if not measure:
            from raft_tpu.tune.fused import target_spec

            prov["target_chip"] = target_spec().name
        tbl = {
            "schema": TUNE_SCHEMA_VERSION,
            "provenance": prov,
            "shape": [nq, m, d, k],
            "n_shards": p,
            "rows": rows,
            "best": best,
            "best_by_passes": best_by_passes,
            "best_by_passes_dtype": best_by_dtype or {},
        }
        errors = validate_tune_table(tbl)
        if errors:
            raise ValueError(f"autotune_sharded produced an invalid "
                             f"table: {errors}")
        if out_path:
            write_tune_table(out_path, tbl)
        return tbl

    if not measure:
        from raft_tpu.tune.fused import target_spec

        spec = target_spec()
        rows.extend(predicted_sharded_row(shape, p, c, spec)
                    for c in cands)
        ranked = [r for r in rows if "predicted_seconds" in r]
        best = min(ranked, key=lambda r: r["predicted_seconds"],
                   default=None)
        by_p, by_pd = _winners(ranked,
                               lambda r: r["predicted_seconds"])
        return _flush(best, by_p, by_pd)

    from raft_tpu.benchmark import Fixture
    from raft_tpu.distance.knn_sharded import (knn_fused_sharded,
                                               prepare_knn_index_sharded)
    from raft_tpu.parallel import make_mesh
    from raft_tpu.random import RngState, make_blobs

    if mesh is None:
        mesh = make_mesh({"x": p}, devices=jax.devices()[:p])
    if data is None:
        X, _ = make_blobs(res, RngState(0), m, d, n_clusters=64,
                          cluster_std=2.0)
    else:
        X = data
    Q = X[:nq]
    jax.block_until_ready(Q)
    fx = Fixture(res=res, reps=reps)
    eff_bytes = nq * m * 4.0
    deadline = time.monotonic() + budget_s
    best = None
    best_by: Dict[str, Dict] = {}
    best_by_dt: Dict[str, Dict] = {}
    indexes: Dict[Tuple, object] = {}   # (T, Qb, g, passes, dt) → idx
    for cand in cands:
        if time.monotonic() > deadline:
            rows.append({"budget_expired_after":
                         len([r for r in rows if "seconds" in r])})
            break
        row = predicted_sharded_row(shape, p, cand)
        try:
            ikey = (cand.T, cand.Qb, cand.g, cand.passes,
                    cand.db_dtype)
            idx = indexes.get(ikey)
            if idx is None:
                idx = prepare_knn_index_sharded(
                    X, mesh=mesh, passes=cand.passes, T=cand.T,
                    Qb=cand.Qb, g=cand.g, grid_order=_GRID_ORDER,
                    db_dtype=cand.db_dtype, res=res)
                indexes[ikey] = idx
            name = (f"tune_sharded[p={p},T={cand.T},Qb={cand.Qb},"
                    f"{cand.merge},nb={cand.micro_batches},"
                    f"p{cand.passes},{cand.db_dtype}]")
            run = fx.run(
                lambda q: knn_fused_sharded(
                    q, idx, k, mesh=mesh, merge=cand.merge,
                    micro_batches=cand.micro_batches)[0],
                Q, name=name)
            row["seconds"] = round(run["seconds"], 5)
            row["gbps"] = round(eff_bytes / run["seconds"] / 1e9, 1)
            for f in ("bytes_accessed", "flops", "roofline_frac",
                      "bound"):
                if f in run:
                    row[f] = run[f]
            res.profiler.capture_fn(
                name, lambda q: knn_fused_sharded(
                    q, idx, k, mesh=mesh, merge=cand.merge,
                    micro_batches=cand.micro_batches)[0], Q)
        except Exception as e:
            row["error"] = f"{type(e).__name__}: {e}"[:200]
        rows.append(row)
        ok = [r for r in rows if "seconds" in r]
        best = min(ok, key=lambda r: r["seconds"]) if ok else None
        best_by, best_by_dt = _winners(ok, lambda r: r["seconds"])
        _flush(best, best_by, best_by_dt)
    return _flush(best, best_by, best_by_dt)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shape", type=int, nargs=4,
                    default=list(NORTHSTAR_SHAPE),
                    metavar=("NQ", "M", "D", "K"))
    ap.add_argument("--shards", type=int, default=None)
    ap.add_argument("--out", default="TUNE_SHARDED.json")
    ap.add_argument("--budget-s", type=float, default=float(
        os.environ.get("TUNE_SHARDED_BUDGET_S", "2400")))
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dry", action="store_true",
                    help="tiny-shape harness validation (no artifact)")
    ap.add_argument("--predict-only", action="store_true",
                    help="force the deterministic model-ranked fallback")
    args = ap.parse_args(argv)
    shape = ((256, 20_000, 64, 32) if args.dry else tuple(args.shape))
    tbl = autotune_sharded(
        shape=shape, p=args.shards,
        out_path=None if args.dry else args.out,
        budget_s=args.budget_s,
        measure=False if args.predict_only else None,
        reps=1 if args.dry else args.reps)
    print(json.dumps({"best": tbl.get("best"),
                      "rows": len(tbl.get("rows", [])),
                      "n_shards": tbl.get("n_shards"),
                      "measured": tbl["provenance"]["measured"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
