"""IVF-Flat: inverted-list ANN over the fused KNN primitives.

(ref: neighbors/ivf_flat.cuh + detail/ivf_flat_build.cuh /
ivf_flat_search.cuh — the reference's interleaved-list IVF index, the
headline ANN capability that migrated to cuVS. BASELINE's "critical
scoping fact": past the streamed-HBM roofline the only speedup left is
reading LESS of the database; IVF-Flat reads ``n_probes/n_lists`` of
it, trading tracked recall.)

Index layout — the **padded ragged slab** (build_ivf_flat):

- database rows are bucketed by nearest coarse centroid (balanced
  k-means, :mod:`raft_tpu.cluster` — balance keeps per-probe cost
  uniform and pad waste bounded);
- each inverted list is padded up to a multiple of the **row quantum**
  (default 8 — the fused pipeline's sublane multiple), then the lists
  are laid back-to-back in ONE [R, d] slab: ``offsets [L+1]`` row
  offsets, ``sizes [L]`` real lengths, global ids carried alongside in
  ``ids [R]`` (−1 on pad rows). Memory is Σ padded sizes — ragged, not
  L·max;
- the slab's pad rows are exactly the ragged ``rows_valid`` layout
  ``distance.knn_fused._prepare_ops`` now takes: the degenerate exact
  path runs the CERTIFIED packed fused kernel over the whole slab with
  interspersed pads carried as never-wins sentinels.

Search (search_ivf_flat):

1. **coarse probe**: top-``n_probes`` nearest centroids per query via
   the existing fused-L2 top-k machinery
   (:func:`raft_tpu.distance.fused_l2nn.knn`, streamed sweep — the
   fusedL2NN lineage);
2. **fine scan**: the probed lists' slab windows are gathered per
   query and scored with the exact expanded-L2 form (f32 HIGHEST — the
   same score the fused pipeline's rescore evaluates, so the
   ``n_probes = n_lists`` result is id-for-id the brute-force oracle),
   then one top-k over the ``n_probes·window`` candidates;
3. ``n_probes ≥ n_lists`` (or ``k`` beyond the probed capacity)
   **degrades to exact search** with a logged reason — the certified
   fused pipeline over the ragged slab — so the speed/recall knob can
   never silently return worse-than-exact results at exact cost.

``shard="lists"`` (shard_ivf_lists + the sharded search path): WHOLE
lists distribute over a mesh axis via shard_map — each shard scans the
probed lists it owns and the per-shard top-k candidates (global ids)
merge with the PR-4 rank-ordered machinery
(:func:`raft_tpu.distance.knn_sharded._merge_allgather` /
``_merge_tournament``, strategy picked by the ICI cost model).

Observability: build and search are ``@instrument``-ed, carry the
``ivf_build`` / ``ivf_search`` fault sites, emit ``marker`` flight
events (probed-bytes fraction rides the search event), and the fine
scan's XLA cost is captured through ``res.profiler.capture_fn``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import env
from raft_tpu.core.error import DeadlineExceededError, expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import explain, instrument
from raft_tpu.observability.flight import get_flight_recorder
from raft_tpu.observability.quality import (record_certificate,
                                            record_pending)
from raft_tpu.observability.timeline import emit_marker
from raft_tpu.resilience import fault_point
from raft_tpu.resilience.policy import record_degradation

#: inverted-list row quantum: every list pads to a multiple of this
#: (the fused pipeline's 8-row sublane multiple — a slab built at this
#: quantum stays gatherable in whole sublanes). Env override:
#: ``RAFT_TPU_IVF_ROW_QUANTUM``.
DEFAULT_ROW_QUANTUM = 8


def _env_int(name: str, default: int, lo: int = 1) -> int:
    import os

    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return max(lo, int(raw))
    except (TypeError, ValueError):
        from raft_tpu.core.logger import log_warn

        log_warn("%s=%r is not an int — using %d", name, raw, default)
        return default

#: fine-scan gather budget: queries chunk so the [nq, P·W, d] candidate
#: tile stays under ~256 MB f32
_FINE_TILE = 1 << 26

#: IVF storage dtypes for the fine-scan slab: "f32" gathers full rows;
#: "int8" gathers the per-list symmetric-scale quantized slab (~¼ the
#: probed bytes), prunes to a certified candidate pool and exact-
#: rescoring it from the f32 rows — chunks whose certificate fails
#: rerun the f32 scan, so returned ids never degrade
IVF_DB_DTYPES = ("f32", "int8")

#: rescue-pool oversampling of the quantized fine scan (candidates
#: exact-rescored per query beyond k)
_IVF_RESCORE_PAD = 32

#: fine-scan schedules: "query" = per-query probe-window gather (the
#: PR-8 XLA path), "list" = list-major stream-once Pallas kernels
#: (each probed list read ONCE per query chunk for all queries probing
#: it), "auto" = the resolve_fine_scan cost-model crossover on the
#: index's actual probed-list histogram. Env: RAFT_TPU_IVF_FINE_SCAN.
FINE_SCANS = ("auto", "query", "list")

#: list-major envelope: k must leave headroom inside the 2×128-slot
#: candidate pool or the completeness certificate would fail every
#: query straight into the query-major rerun
_LIST_K_MAX = 96

# compiled sharded-search programs, keyed by full static geometry
# (same pattern as knn_sharded._SHARDED_FUSED_CACHE)
_SHARDED_IVF_CACHE: dict = {}


class IvfFlatIndex:
    """The padded ragged IVF-Flat index (see the module doc). Built by
    :func:`build_ivf_flat`; queried by :func:`search_ivf_flat`. The
    coarse centroids, slab geometry and metric are frozen at build.

    ``Qb`` is the serving-bucket hint (the fused pipeline's tuned query
    block) so the serving engine's bucket ladder derives the same way
    it does for a brute-force :class:`~raft_tpu.distance.knn_fused.
    KnnIndex` snapshot."""

    def __init__(self, centroids, slab, ids, yy_slab, offsets, sizes,
                 padded_sizes, n_rows: int, d_orig: int,
                 row_quantum: int, n_probes_default: int, Qb: int,
                 kmeans_iters: int = 0, balanced: bool = True,
                 db_dtype: str = "f32", slab_q=None, row_scale=None,
                 yy_q=None, eq_rows=None):
        self.centroids = centroids          # [L, d] f32
        self.slab = slab                    # [R, d] f32 (pad rows zero)
        self.ids = ids                      # [R] int32 global ids, -1 pads
        self.yy_slab = yy_slab              # [R] f32 row norms (pads 0)
        self.offsets = offsets              # [L+1] int32 slab row offsets
        self.sizes = sizes                  # [L] int32 real list lengths
        self.padded_sizes = padded_sizes    # [L] int32 quantum-padded
        self.n_rows = n_rows
        self.d_orig = d_orig
        self.row_quantum = row_quantum
        self.n_probes_default = n_probes_default
        self.Qb = Qb
        self.kmeans_iters = kmeans_iters
        self.balanced = balanced
        self.metric = "l2"
        # quantized fine-scan state (db_dtype="int8"): per-LIST
        # symmetric int8 slab + per-row scale/Eq (rows of a list share
        # its scale — stored per row so the probe-window gather pulls
        # them alongside the codes), and the DEQUANTIZED row norms the
        # approximate scorer uses. The f32 slab stays: it is the exact-
        # rescore (and degenerate-exact / sharded) data plane.
        self.db_dtype = db_dtype
        self.slab_q = slab_q                # [R, d] int8 or None
        self.row_scale = row_scale          # [R] f32
        self.yy_q = yy_q                    # [R] f32 (‖ŷ‖², pads 0)
        self.eq_rows = eq_rows              # [R] f32 per-row Eq bound
        # host copies of the geometry (numpy — search wrappers index
        # them without device sync) + the lazy ragged fused operands
        self._np_offsets = np.asarray(offsets)
        self._np_sizes = np.asarray(sizes)
        self._np_padded = np.asarray(padded_sizes)
        self._fused_ops = None
        # lazy per-list host/device geometry for the list-major fine
        # scan (per-list scale + Eq + max row norms)
        self._list_host = None

    @property
    def n_lists(self) -> int:
        return int(self.sizes.shape[0])

    @property
    def probe_window(self) -> int:
        """Static per-probe gather window: the largest padded list."""
        return max(int(self._np_padded.max()), self.row_quantum)

    @property
    def slab_rows(self) -> int:
        return int(self.slab.shape[0])

    def __repr__(self):
        return (f"IvfFlatIndex(n_rows={self.n_rows}, "
                f"n_lists={self.n_lists}, d={self.d_orig}, "
                f"slab_rows={self.slab_rows}, "
                f"window={self.probe_window})")

    def layout(self):
        """This index's slab as the shared explicit
        :class:`~raft_tpu.mutable.layout.IndexLayout` struct — the
        degenerate-exact plane, the mutable subsystem and the brute
        plane all drive the same pure ops over it."""
        from raft_tpu.mutable.layout import IndexLayout

        return IndexLayout(
            self.slab, self.ids, np.asarray(self.ids) >= 0,
            n_rows=self.n_rows, d_orig=self.d_orig,
            offsets=self._np_offsets, sizes=self._np_sizes,
            padded_sizes=self._np_padded, row_quantum=self.row_quantum,
            db_dtype=self.db_dtype if self.db_dtype == "int8" else "f32",
            slab_q=self.slab_q, row_scale=self.row_scale,
            eq_rows=self.eq_rows)


@instrument("ann.build_ivf_flat")
def build_ivf_flat(res, y, n_lists: int, n_probes: Optional[int] = None,
                   max_iter: int = 10, seed: int = 0,
                   balanced: bool = True,
                   row_quantum: Optional[int] = None,
                   max_train_rows: Optional[int] = None,
                   db_dtype: str = "f32") -> IvfFlatIndex:
    """Build an :class:`IvfFlatIndex` over ``y`` [m, d].

    (ref: ivf_flat::build — coarse-train on a sub-sample, assign every
    row, bucket into interleaved lists.) Coarse training runs balanced
    k-means (:func:`raft_tpu.cluster.kmeans_fit`) on at most
    ``max_train_rows`` rows (default ``max(32·n_lists, 4096)`` — the
    trainset_fraction idea), full assignment runs the fusedL2NN argmin
    sweep, and the host lays the lists out as the padded ragged slab
    described in the module doc.

    ``db_dtype="int8"`` (:data:`IVF_DB_DTYPES`) additionally packs the
    slab with per-list symmetric int8 scales (the cuVS int8 IVF-Flat
    shape): the fine scan gathers ~¼ the probed bytes, prunes to a
    certified candidate pool and exact-rescoring it from the kept f32
    rows — id sets never degrade (failed certificates rerun the f32
    scan)."""
    from raft_tpu.cluster import kmeans_fit, kmeans_predict

    fault_point("ivf_build")
    res = ensure_resources(res)
    if db_dtype not in IVF_DB_DTYPES:
        raise ValueError(f"build_ivf_flat: db_dtype must be one of "
                         f"{IVF_DB_DTYPES}, got {db_dtype!r}")
    if row_quantum is None:
        row_quantum = _env_int("RAFT_TPU_IVF_ROW_QUANTUM",
                               DEFAULT_ROW_QUANTUM)
    y = np.asarray(y, np.float32)
    m, d = y.shape
    L = int(n_lists)
    expects(L >= 1, "build_ivf_flat: n_lists must be >= 1, got %d", L)
    expects(L <= m, "build_ivf_flat: n_lists=%d > %d rows", L, m)
    expects(row_quantum >= 1,
            "build_ivf_flat: row_quantum must be >= 1")
    cap = max_train_rows or max(32 * L, 4096)
    if m > cap:
        rng = np.random.default_rng(seed)
        train = y[rng.choice(m, cap, replace=False)]
    else:
        train = y
    km = kmeans_fit(res, train, L, max_iter=max_iter, seed=seed,
                    balanced=balanced)
    labels = np.asarray(kmeans_predict(res, km.centroids, y))

    # ---- host-side ragged layout: the shared IndexLayout op (the
    # mutable subsystem and this builder spell the padded ragged slab
    # through ONE function — raft_tpu.mutable.layout) ----------------
    from raft_tpu.mutable.layout import (quantize_layout,
                                         ragged_layout_from_lists)

    lay = ragged_layout_from_lists(y, labels, L, row_quantum)
    sizes, padded, offsets = lay.sizes, lay.padded_sizes, lay.offsets
    R = lay.slab_rows
    slab, ids = lay.slab, lay.ids

    from raft_tpu.distance.knn_fused import fused_config

    n_probes_default = int(n_probes) if n_probes else max(
        1, min(L, 1 + L // 8))
    q8_kw = {}
    if db_dtype == "int8":
        fault_point("quantize_index")
        lay = quantize_layout(lay)
        deq = lay.slab_q.astype(jnp.float32) * lay.row_scale[:, None]
        q8_kw = dict(db_dtype="int8", slab_q=lay.slab_q,
                     row_scale=lay.row_scale,
                     yy_q=jnp.sum(deq * deq, axis=1),
                     eq_rows=lay.eq_rows)
    idx = IvfFlatIndex(
        centroids=km.centroids,
        slab=jnp.asarray(slab),
        ids=jnp.asarray(ids),
        yy_slab=jnp.sum(jnp.asarray(slab) ** 2, axis=1),
        offsets=jnp.asarray(offsets),
        sizes=jnp.asarray(sizes),
        padded_sizes=jnp.asarray(padded),
        n_rows=m, d_orig=d, row_quantum=int(row_quantum),
        n_probes_default=n_probes_default,
        Qb=fused_config(3).Qb,
        kmeans_iters=km.n_iter, balanced=balanced, **q8_kw)
    emit_marker("ivf_build", n_rows=m, n_lists=L, slab_rows=R,
                window=idx.probe_window,
                pad_frac=round(float(R - m) / max(m, 1), 4),
                size_min=int(sizes.min()), size_max=int(sizes.max()),
                kmeans_iters=km.n_iter, balanced=bool(balanced),
                db_dtype=db_dtype)
    return idx


# --------------------------------------------------------- fine scan
@partial(jax.jit, static_argnames=("k", "P", "W"))
def _fine_scan(x, slab, ids, yy_slab, starts, psizes,
               k: int, P: int, W: int):
    """Score the probed slab windows and select top-k.

    ``starts [nq, P]`` are slab row offsets of the probed lists,
    ``psizes [nq, P]`` their padded lengths (0 = unowned/empty probe).
    The expanded-L2 score is evaluated in f32 HIGHEST — the same form
    (and therefore bitwise the same candidate values) the fused
    pipeline's exact rescore computes, which is what makes the
    ``n_probes = n_lists`` id sets match the oracle exactly."""
    nq = x.shape[0]
    ar = jnp.arange(W, dtype=jnp.int32)
    rows = starts[:, :, None] + ar[None, None, :]          # [nq, P, W]
    within = ar[None, None, :] < psizes[:, :, None]
    rows = jnp.clip(rows, 0, slab.shape[0] - 1).reshape(nq, P * W)
    within = within.reshape(nq, P * W)
    cid = jnp.take(ids, rows)
    valid = within & (cid >= 0)
    yc = jnp.take(slab, rows, axis=0)                      # [nq, PW, d]
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    d2 = (xx + jnp.take(yy_slab, rows)
          - 2.0 * jnp.einsum("qd,qcd->qc", x, yc,
                             precision=jax.lax.Precision.HIGHEST))
    d2 = jnp.where(valid, jnp.maximum(d2, 0.0), jnp.inf)
    neg, pos = jax.lax.top_k(-d2, k)
    vals = -neg
    out_ids = jnp.take_along_axis(cid, pos, axis=1)
    return vals, jnp.where(jnp.isfinite(vals), out_ids, -1)


@partial(jax.jit, static_argnames=("k", "P", "W", "C"))
def _fine_scan_q8(x, slab, slab_q, row_scale, ids, yy_q, starts, psizes,
                  k: int, P: int, W: int, C: int, eq_rows=None):
    """Quantized fine scan: gather the probed windows from the INT8
    slab (+ per-row scale/norm/Eq — ~(d+12)/(4d+8) of the f32 gather
    bytes), score approximately against the dequantized rows ŷ, keep
    the top ``C = k + pad`` candidates, exact-rescore THEM from the f32
    slab, and certify per query that the true top-k cannot hide outside
    the pool: every non-candidate has d2(x, ŷ) ≥ B (the C-th approx
    score), so a violator with true d2 < θ would need
    B ≤ (√θ + Eq)² + e_num — Eq the max quantization bound among the
    probed rows, e_num a conservative f32-accumulation envelope.
    Returns (vals, ids, certified, margin) — the caller reruns failed
    queries through the exact f32 scan, so ids never degrade; margin
    (bound − θ − widen, pre-rerun) feeds the explain plane."""
    nq = x.shape[0]
    ar = jnp.arange(W, dtype=jnp.int32)
    rows = starts[:, :, None] + ar[None, None, :]          # [nq, P, W]
    within = ar[None, None, :] < psizes[:, :, None]
    rows = jnp.clip(rows, 0, slab_q.shape[0] - 1).reshape(nq, P * W)
    within = within.reshape(nq, P * W)
    cid = jnp.take(ids, rows)
    valid = within & (cid >= 0)
    yq = jnp.take(slab_q, rows, axis=0).astype(jnp.float32)
    scl = jnp.take(row_scale, rows)
    yc = yq * scl[:, :, None]                              # ŷ [nq, PW, d]
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    yyq = jnp.take(yy_q, rows)
    d2h = (xx + yyq
           - 2.0 * jnp.einsum("qd,qcd->qc", x, yc,
                              precision=jax.lax.Precision.HIGHEST))
    d2h = jnp.where(valid, jnp.maximum(d2h, 0.0), jnp.inf)
    neg_c, cpos = jax.lax.top_k(-d2h, C)                   # approx pool
    bound = -neg_c[:, C - 1]
    crow = jnp.take_along_axis(rows, cpos, axis=1)
    ccid = jnp.take_along_axis(cid, cpos, axis=1)
    cvalid = jnp.take_along_axis(valid, cpos, axis=1)
    # exact f32 rescore of the C survivors — bitwise the same score
    # the f32 fine scan computes for these rows
    ycf = jnp.take(slab, crow, axis=0)                     # [nq, C, d]
    d2 = (xx + jnp.sum(ycf * ycf, axis=2)
          - 2.0 * jnp.einsum("qd,qcd->qc", x, ycf,
                             precision=jax.lax.Precision.HIGHEST))
    d2 = jnp.where(cvalid, jnp.maximum(d2, 0.0), jnp.inf)
    neg_k, kpos = jax.lax.top_k(-d2, k)
    vals = -neg_k
    out_ids = jnp.take_along_axis(ccid, kpos, axis=1)
    out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
    # ---- certificate ----
    theta = vals[:, k - 1]
    eqg = jnp.take(eq_rows, rows)
    eq_w = jnp.max(jnp.where(valid, eqg, 0.0), axis=1)
    yymax = jnp.max(jnp.where(valid, yyq, 0.0), axis=1)
    d_feat = x.shape[1]
    e_num = (d_feat * 2.0 ** -22) * (
        jnp.sqrt(xx[:, 0]) + jnp.sqrt(yymax)) ** 2
    sq_t = jnp.sqrt(jnp.maximum(theta, 0.0))
    widen = 2.0 * sq_t * eq_w + eq_w * eq_w + e_num
    # a pool that covers every probed candidate is trivially complete
    n_valid = jnp.sum(valid.astype(jnp.int32), axis=1)
    certified = (bound >= theta + widen) | (n_valid <= C) \
        | ~jnp.isfinite(bound)
    # explain-plane margin: non-finite where the certificate was
    # trivially complete (finalize filters those out)
    margin = bound - (theta + widen)
    return vals, out_ids, certified, margin


# ----------------------------------------- list-major fine scan
# (ISSUE 14: stream each probed list ONCE per query chunk for every
# query probing it — the inverted-index batching trade, run through
# the ops.fine_scan_pallas kernel family. Ids stay bit-identical to
# the query-major oracle: pooled candidates are exact-rescored with
# the query-major scorer's own formula, reordered into its probe-slot
# candidate order (so ties break identically), and a per-query
# completeness certificate reruns any uncovered query query-major.)

class _ListSchedule:
    """Host-built list-major schedule for one query chunk: the
    transposed probe table. ``sched [4, Lp]`` int32 rows are (clamped
    window start, real list length, list offset within the window,
    list id); Lp is padded to the 8-list cell quantum with the cell
    count rounded to a power of two (capped at the index's own cell
    count), so one compiled program serves a whole probes sweep. The
    [L_probed, q_max] query-group table (q_max padded to 8) + its
    never-wins mask ride along for the cost model and tests — the
    kernel itself consumes the resident probe table directly."""

    __slots__ = ("sched", "scale_l", "n_lists_probed", "q_max",
                 "group", "group_mask", "stream_rows")

    def __init__(self, sched, scale_l, n_lists_probed, q_max, group,
                 group_mask, stream_rows):
        self.sched = sched
        self.scale_l = scale_l
        self.n_lists_probed = n_lists_probed
        self.q_max = q_max
        self.group = group
        self.group_mask = group_mask
        self.stream_rows = stream_rows


def _list_cells(n_probed: int, n_lists: int) -> int:
    """Schedule cell count: probed lists bucket into 8-list cells,
    rounded up to a power of two (compile-cache stability across
    batches) and capped at the whole index's cell count."""
    from raft_tpu.ops.fine_scan_pallas import LISTS_PER_CELL

    cells = max(1, -(-n_probed // LISTS_PER_CELL))
    cap = max(1, -(-n_lists // LISTS_PER_CELL))
    return min(1 << (cells - 1).bit_length(), cap)


def build_list_schedule(index: IvfFlatIndex, probes_np) -> _ListSchedule:
    """Invert a chunk's per-query probe lists [nq, P] into the
    per-list query-group schedule (see :class:`_ListSchedule`).
    Host-side numpy — the probe table is tiny next to the slab."""
    from raft_tpu.ops.fine_scan_pallas import (LISTS_PER_CELL,
                                               pad_window)

    probes_np = np.asarray(probes_np)
    nq, P = probes_np.shape
    plist = np.unique(probes_np.ravel())
    plist = plist[plist >= 0].astype(np.int64)
    Lp = int(plist.size)
    Wk = pad_window(index.probe_window)
    R = index.slab_rows
    Lp_pad = _list_cells(Lp, index.n_lists) * LISTS_PER_CELL
    sched = np.zeros((4, Lp_pad), np.int32)
    sched[3, :] = -1
    starts = index._np_offsets[plist].astype(np.int64)
    clamped = np.clip(np.minimum(starts, R - Wk), 0, None)
    sched[0, :Lp] = clamped
    sched[1, :Lp] = index._np_sizes[plist]
    sched[2, :Lp] = starts - clamped
    sched[3, :Lp] = plist
    scale_l = np.ones(Lp_pad, np.float32)
    if index.db_dtype == "int8":
        scale_l[:Lp] = _list_host(index)["scale"][plist]
    # the transposed [L_probed, q_max] query-group table: group g holds
    # the query indices probing plist[g], padded to the 8-row quantum
    # with the never-wins mask marking real entries
    inv = {int(l): g for g, l in enumerate(plist)}
    members: list = [[] for _ in range(Lp)]
    for q in range(nq):
        for l in probes_np[q]:
            if l >= 0:
                members[inv[int(l)]].append(q)
    q_max = -(-max((len(m) for m in members), default=1) // 8) * 8
    group = np.zeros((max(Lp, 1), q_max), np.int32)
    gmask = np.zeros((max(Lp, 1), q_max), bool)
    for g, m in enumerate(members):
        group[g, :len(m)] = m
        gmask[g, :len(m)] = True
    stream_rows = int(index._np_padded[plist].sum())
    return _ListSchedule(sched, scale_l, Lp, int(q_max), group, gmask,
                         stream_rows)


def _list_host(index: IvfFlatIndex) -> dict:
    """Lazy per-list host geometry for the list-major path: the
    symmetric int8 scale, the Eq quantization bound and the max
    (dequantized) row norm of each list — certificate inputs gathered
    per probe at search time. Computed once per index."""
    if index._list_host is not None:
        return index._list_host
    offs = index._np_offsets
    L = index.n_lists
    padded = index._np_padded
    yy = np.asarray(index.yy_q if index.db_dtype == "int8"
                    else index.yy_slab)
    yy_lmax = np.zeros(L, np.float32)
    for l in range(L):
        w = int(padded[l])
        if w:
            yy_lmax[l] = yy[int(offs[l]):int(offs[l]) + w].max()
    host = {"yy_lmax": jnp.asarray(yy_lmax)}
    if index.db_dtype == "int8":
        scale = np.asarray(index.row_scale)
        eq = np.asarray(index.eq_rows)
        scale_list = np.ones(L, np.float32)
        eq_list = np.zeros(L, np.float32)
        for l in range(L):
            if int(padded[l]):
                scale_list[l] = scale[int(offs[l])]
                eq_list[l] = eq[int(offs[l])]
        host["scale"] = scale_list
        host["eq_list"] = jnp.asarray(eq_list)
    index._list_host = host
    return host


def _pool_finish(x, xx, rows, slab, ids, yy_slab, starts_qm, psizes,
                 k: int, P: int, W: int):
    """Exact-rescore the pooled candidate rows with the query-major
    scorer's own formula (bitwise the values :func:`_fine_scan`
    computes for the same rows), reorder them into the query-major
    candidate order — probe slot × window column, so ``top_k``'s
    lowest-index tie-breaking picks the same winners — and select
    top-k."""
    valid = rows >= 0
    rc = jnp.maximum(rows, 0)
    yc = jnp.take(slab, rc, axis=0)                    # [nq, C2, d]
    d2 = (xx + jnp.take(yy_slab, rc)
          - 2.0 * jnp.einsum("qd,qcd->qc", x, yc,
                             precision=jax.lax.Precision.HIGHEST))
    d2 = jnp.where(valid, jnp.maximum(d2, 0.0), jnp.inf)
    # canonical query-major position of each pooled row: its probe
    # slot p and column within that window
    w = rows[:, :, None] - starts_qm[:, None, :]       # [nq, C2, P]
    match = ((w >= 0) & (w < psizes[:, None, :])
             & valid[:, :, None])
    slot = jnp.argmax(match, axis=2).astype(jnp.int32)
    col = jnp.take_along_axis(w, slot[:, :, None], axis=2)[:, :, 0]
    key = jnp.where(jnp.any(match, axis=2),
                    slot * W + col.astype(jnp.int32), P * W)
    order = jnp.argsort(key, axis=1)
    d2s = jnp.take_along_axis(d2, order, axis=1)
    rs = jnp.take_along_axis(rows, order, axis=1)
    cid = jnp.where(rs >= 0, jnp.take(ids, jnp.maximum(rs, 0)), -1)
    neg, pos = jax.lax.top_k(-d2s, k)
    vals = -neg
    out_ids = jnp.take_along_axis(cid, pos, axis=1)
    return vals, jnp.where(jnp.isfinite(vals), out_ids, -1)


def _pad_kernel_operands(x, probes):
    """Query block + probe table padded to the kernel envelope: rows
    to the 8-sublane quantum (pad probes −2 — matches no list id, so
    pad queries pool nothing) and the probe table to the 128-lane
    tile."""
    nq, P = probes.shape
    nqp = -(-nq // 8) * 8
    xp = jnp.concatenate(
        [x, jnp.zeros((nqp - nq, x.shape[1]), jnp.float32)]) \
        if nqp > nq else x
    pp = jnp.full((nqp, 128), -2, jnp.int32)
    pp = jax.lax.dynamic_update_slice(pp, probes.astype(jnp.int32),
                                      (0, 0))
    return xp, pp, nqp


def _kernel_envelope(bound, theta, widen):
    """certified ⇔ no probed row outside the pool can beat the exact
    k-th value: every excluded row scored ≥ its slot's 3rd-min ≥
    ``bound``; an +inf bound means every slot kept all its rows (the
    pool is trivially complete)."""
    return bound >= theta + widen


@partial(jax.jit, static_argnames=("k", "P", "W", "Wk"))
def _fine_scan_list(x, sched, probes, slab, ids, yy_slab, starts_qm,
                    psizes, yy_lmax, k: int, P: int, W: int, Wk: int):
    """List-major fine scan over the f32 slab (see the block comment):
    kernel pools → exact rescore + canonical reorder → certificate.
    Returns (vals, ids, certified, margin) like :func:`_fine_scan_q8`
    — the caller reruns failed queries query-major, so ids never
    drift."""
    from raft_tpu.ops.fine_scan_pallas import fine_scan_list_major

    nq, d = x.shape
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    xp, pp, nqp = _pad_kernel_operands(x, probes)
    xxp = jnp.concatenate(
        [xx, jnp.zeros((nqp - nq, 1), jnp.float32)]) if nqp > nq else xx
    a1, i1, a2, i2, a3 = fine_scan_list_major(sched, xp, xxp, pp, slab,
                                              Wk=Wk)
    rows = jnp.concatenate([i1[:nq], i2[:nq]], axis=1)   # [nq, 256]
    vals, out_ids = _pool_finish(x, xx, rows, slab, ids, yy_slab,
                                 starts_qm, psizes, k, P, W)
    theta = vals[:, k - 1]
    bound = jnp.min(a3[:nq], axis=1)
    # kernel-precision envelope: bf16 hi/lo cross term + the in-kernel
    # MXU-contracted row norms (2⁻¹⁶-grade splits) + f32 accumulation
    yymax = jnp.max(jnp.take(yy_lmax, probes), axis=1)
    span = (jnp.sqrt(xx[:, 0]) + jnp.sqrt(yymax)) ** 2
    widen = (2.0 ** -13 + d * 2.0 ** -22) * span
    certified = _kernel_envelope(bound, theta, widen)
    return vals, out_ids, certified, bound - (theta + widen)


@partial(jax.jit, static_argnames=("k", "P", "W", "Wk"))
def _fine_scan_list_q8(x, sched, scale_l, probes, slab_q, slab, ids,
                       yy_slab, yy_lmax, eq_list, starts_qm, psizes,
                       k: int, P: int, W: int, Wk: int):
    """INT8 list-major fine scan: streams the quantized slab (~¼ the
    probed bytes) through :func:`ops.fine_scan_pallas.
    fine_scan_list_major_q8` with per-list dequant-in-register scales,
    then the same exact-rescore/reorder/certificate pipeline — the
    certificate additionally widens by the probed lists' recorded Eq
    bound exactly like the query-major :func:`_fine_scan_q8`."""
    from raft_tpu.ops.fine_scan_pallas import fine_scan_list_major_q8

    nq, d = x.shape
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    xp, pp, nqp = _pad_kernel_operands(x, probes)
    xxp = jnp.concatenate(
        [xx, jnp.zeros((nqp - nq, 1), jnp.float32)]) if nqp > nq else xx
    a1, i1, a2, i2, a3 = fine_scan_list_major_q8(
        sched, scale_l, xp, xxp, pp, slab_q, Wk=Wk)
    rows = jnp.concatenate([i1[:nq], i2[:nq]], axis=1)
    vals, out_ids = _pool_finish(x, xx, rows, slab, ids, yy_slab,
                                 starts_qm, psizes, k, P, W)
    theta = vals[:, k - 1]
    bound = jnp.min(a3[:nq], axis=1)
    yymax = jnp.max(jnp.take(yy_lmax, probes), axis=1)
    eq_w = jnp.max(jnp.take(eq_list, probes), axis=1)
    span = (jnp.sqrt(xx[:, 0]) + jnp.sqrt(yymax)) ** 2
    e_k = (2.0 ** -13 + d * 2.0 ** -22) * span
    sq_t = jnp.sqrt(jnp.maximum(theta, 0.0))
    widen = 2.0 * sq_t * eq_w + eq_w * eq_w + e_k
    certified = _kernel_envelope(bound, theta, widen)
    return vals, out_ids, certified, bound - (theta + widen)


def resolve_fine_scan(index: IvfFlatIndex, nq: int, k: int, P: int,
                      W: int, requested: Optional[str] = None,
                      probes_np=None, chunk: Optional[int] = None
                      ) -> str:
    """EFFECTIVE fine-scan schedule for a call — decided (and logged)
    in the non-jitted wrapper like ``resolve_grid_order``. ``None``
    reads ``RAFT_TPU_IVF_FINE_SCAN`` (default ``auto``).

    Envelope (outside it every request runs query-major, with a
    logged downgrade for an explicit ``list``): the slab must cover
    one kernel window, k the candidate pool, the probe count the
    128-lane probe table, the cell fit the scoped-VMEM budget, and on
    real TPUs the feature width must be lane-aligned.

    ``auto`` consults the schema-5 ``fine_scan`` tune-table column
    (:func:`raft_tpu.tune.ivf.fine_scan_config`) first, then falls to
    the cost-model crossover on the index's ACTUAL probed-list-size
    histogram (:func:`~raft_tpu.observability.costmodel.
    choose_fine_scan` over :func:`~raft_tpu.observability.costmodel.
    ivf_traffic_model`)."""
    from raft_tpu.observability.costmodel import (DB_DTYPE_BYTES,
                                                  FINE_SCAN_MARGIN,
                                                  choose_fine_scan,
                                                  ivf_traffic_model)
    from raft_tpu.ops.fine_scan_pallas import (fine_scan_vmem_footprint,
                                               pad_window)
    from raft_tpu.ops.fused_l2_topk_pallas import vmem_budget
    from raft_tpu.ops.utils import interpret_mode

    req = requested if requested is not None \
        else env.get("RAFT_TPU_IVF_FINE_SCAN")
    if req not in FINE_SCANS:
        raise ValueError(f"fine_scan must be one of {FINE_SCANS}, "
                         f"got {req!r}")
    if req == "query":
        return "query"
    Wk = pad_window(W)
    d = index.d_orig
    quant = index.db_dtype == "int8"
    nqp = -(-min(nq, chunk or nq) // 8) * 8
    reason = None
    if index.slab_rows < Wk:
        reason = (f"slab rows {index.slab_rows} < kernel window {Wk}")
    elif k > _LIST_K_MAX:
        reason = f"k={k} > {_LIST_K_MAX} exceeds the candidate pool"
    elif P > 128:
        reason = f"n_probes={P} > 128 exceeds the probe table"
    elif fine_scan_vmem_footprint(Wk, nqp, d, quant) > vmem_budget():
        reason = "cell footprint over the scoped-VMEM budget"
    elif not interpret_mode() and d % 128:
        reason = f"d={d} is not lane-aligned on a real TPU"
    if reason is not None:
        if req == "list":
            from raft_tpu.core.logger import log_warn

            log_warn("fine_scan='list' outside the list-major envelope "
                     "(%s) — using 'query' for this call", reason)
        return "query"
    if req == "list":
        return "list"
    # auto — tuned table first, then the cost-model crossover
    from raft_tpu.tune.ivf import fine_scan_config

    tuned = fine_scan_config(index.n_lists, P)
    if tuned in ("query", "list"):
        return tuned
    sizes = index._np_sizes
    padded = index._np_padded
    if probes_np is not None:
        # the actual probe table: exact per-chunk union of probed
        # lists vs the exact gather, same margin as the model path
        probes_np = np.asarray(probes_np)
        step = max(1, int(chunk or nq))
        bpe = DB_DTYPE_BYTES[index.db_dtype
                             if quant else "f32"]
        per_row = d * bpe + 8 + (8 if quant else 0)
        stream = 0.0
        for s in range(0, probes_np.shape[0], step):
            u = np.unique(probes_np[s:s + step].ravel())
            stream += float(padded[u[u >= 0]].sum()) * per_row
        stream += float(nq) * min(256, P * W) * d * 4.0
        gather = float(nq) * P * W * per_row
        if quant:
            gather += float(nq) * min(k + _IVF_RESCORE_PAD, P * W) \
                * d * 4.0
        return "list" if gather > FINE_SCAN_MARGIN * max(stream, 1.0) \
            else "query"
    model = ivf_traffic_model(
        nq, index.n_rows, d, k, index.n_lists, P, W, index.slab_rows,
        db_dtype=index.db_dtype if quant else "f32",
        list_sizes=sizes, padded_sizes=padded)
    return choose_fine_scan(model)


def warm_fine_scan(res, index: IvfFlatIndex, nq: int, k: int,
                   n_probes: int) -> int:
    """Pre-compile BOTH fine-scan schedules a serving bucket of ``nq``
    queries can reach: the query-major gather programs (through the
    public wrapper, so its chunking/rerun programs warm too) and one
    list-major program per power-of-two schedule-cell rung — the only
    geometry axis that varies with batch content; everything else is
    frozen by the index. Called from the snapshot warmup so a live
    request can never pay a compile whichever way the
    :func:`resolve_fine_scan` crossover lands. Returns the list-major
    rung count (0 = the bucket is outside the list-major envelope)."""
    from raft_tpu.ops.fine_scan_pallas import (LISTS_PER_CELL,
                                               pad_window)

    P = min(max(1, int(n_probes)), index.n_lists)
    if P >= index.n_lists or nq < 1:
        return 0            # the degenerate-exact plane — one schedule
    W = index.probe_window
    Wk = pad_window(W)
    d = index.d_orig
    x0 = np.zeros((nq, d), np.float32)
    out = search_ivf_flat(res, index, x0, k, n_probes=P,
                          fine_scan="query")
    jax.block_until_ready(out)
    if resolve_fine_scan(index, nq, k, P, W, "list") != "list":
        return 0
    chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
    sizes = sorted({min(nq, chunk), nq % chunk or min(nq, chunk)})
    cap = max(1, -(-index.n_lists // LISTS_PER_CELL))
    rungs = sorted({min(1 << b, cap)
                    for b in range(cap.bit_length() + 1)})
    host = _list_host(index)
    for nq_c in sizes:
        xc = jnp.zeros((nq_c, d), jnp.float32)
        probes0 = jnp.zeros((nq_c, P), jnp.int32)
        starts0 = jnp.zeros((nq_c, P), jnp.int32)
        psz0 = jnp.zeros((nq_c, P), jnp.int32)
        for cells in rungs:
            Lp = cells * LISTS_PER_CELL
            sched = np.zeros((4, Lp), np.int32)
            sched[3, :] = -1
            if index.db_dtype == "int8":
                out = _fine_scan_list_q8(
                    xc, jnp.asarray(sched), jnp.ones(Lp, jnp.float32),
                    probes0, index.slab_q, index.slab, index.ids,
                    index.yy_slab, host["yy_lmax"], host["eq_list"],
                    starts0, psz0, k=k, P=P, W=W, Wk=Wk)
            else:
                out = _fine_scan_list(
                    xc, jnp.asarray(sched), probes0, index.slab,
                    index.ids, index.yy_slab, starts0, psz0,
                    host["yy_lmax"], k=k, P=P, W=W, Wk=Wk)
            jax.block_until_ready(out)
    return len(rungs)


def _coarse_probe(res, centroids, x, n_probes: int):
    """Top-``n_probes`` nearest coarse centroids per query through the
    existing fused-L2 top-k machinery (the streamed sweep — centroid
    counts are small, so the threshold-gated merge path is the right
    tool on every backend)."""
    from raft_tpu.distance.fused_l2nn import knn as _knn

    _, lists = _knn(res, centroids, x, n_probes, metric="sqeuclidean",
                    algo="streamed")
    return lists


# ------------------------------------------------- exact degradation
def _slab_fused_geometry(index: IvfFlatIndex):
    """Lazy certified-fused operands for the WHOLE slab with the ragged
    ``rows_valid`` mask — the degenerate-exact data plane. Re-expressed
    over the shared layout ops (:func:`raft_tpu.mutable.layout.
    fused_ops_for_layout` — ONE spelling of the packed ragged geometry
    for this plane, the brute plane and the mutable subsystem); the
    exact plane always prepares the f32 slab (it IS the rescore
    source), whatever the index streams."""
    if index._fused_ops is not None:
        return index._fused_ops
    from raft_tpu.mutable.layout import fused_ops_for_layout

    fops = fused_ops_for_layout(index.layout(), passes=3, metric="l2",
                                db_dtype=None)
    index._fused_ops = (fops.ops, fops.rv, fops.T, fops.Qb, fops.g,
                        fops.pbits)
    return index._fused_ops


def _exact_search(res, index: IvfFlatIndex, x, k: int):
    """Exact top-k over the ragged slab through the certified packed
    fused pipeline (``rows_valid`` mask), slab positions mapped back to
    global ids — bitwise the oracle's values (same exact-f32 rescore
    score function over the same rows)."""
    from raft_tpu.distance.knn_fused import (_LANES, _POOL_PAD,
                                             _Q_CHUNK, _knn_fused_core)

    ops, rv, T, Qb, g, pbits = _slab_fused_geometry(index)
    yp, y_hi, y_lo, yyh_k, yy_raw = ops
    M = yp.shape[0]
    n_tiles = M // T
    S_pool = -(-n_tiles // g) * _LANES
    expects(k <= 2 * S_pool,
            "search_ivf_flat: k=%d too large for the exact-path pool "
            "%d (shrink k or grow the index)", k, 2 * S_pool)
    x = jnp.asarray(x, jnp.float32)
    nq = x.shape[0]
    if nq > _Q_CHUNK:
        outs = [_exact_search(res, index, x[s:s + _Q_CHUNK], k)
                for s in range(0, nq, _Q_CHUNK)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))
    dpad = y_hi.shape[1] - x.shape[1]
    if dpad:
        x = jnp.concatenate(
            [x, jnp.zeros((nq, dpad), jnp.float32)], axis=1)
    Qb_eff = min(Qb, ((nq + 7) // 8) * 8)
    qpad = (-nq) % Qb_eff
    if qpad:
        x = jnp.concatenate(
            [x, jnp.zeros((qpad, x.shape[1]), jnp.float32)])
    vals, pos, n_fail, margin = _knn_fused_core(
        x, yp, y_hi, y_lo, yyh_k, yy_raw, k=k, T=T, Qb=Qb_eff, g=g,
        passes=3, metric="l2", m=M, rescore=True, pbits=pbits,
        with_stats=True, rows_valid=rv)
    # certificate telemetry for the degenerate-exact plane (device
    # scalar — resolved at the next quality.drain())
    from raft_tpu.distance.knn_fused import (fixup_tiers_for,
                                             rescore_pool_width)

    record_pending("ann.ivf_exact", n_fail, n_queries=x.shape[0],
                   pool_width=rescore_pool_width(k, S_pool, True),
                   fix_tiers=fixup_tiers_for(M))
    if explain.active() is not None:
        explain.note_margin("ann.ivf_exact",
                            margin[:nq] if qpad else margin)
    vals, pos = vals[:nq], pos[:nq]
    gids = jnp.where(pos >= 0,
                     jnp.take(index.ids, jnp.maximum(pos, 0)), -1)
    return vals, gids


# ------------------------------------------------------------ search
def _query_major_chunk(index: IvfFlatIndex, xs, st, ps, k: int,
                       P: int, W: int):
    """One query-major chunk: the per-query probe-window gather scan
    (f32, or the certified int8 gather with its f32 rerun) — the PR-8
    path, now shared by the query-major schedule and the list-major
    certificate-failure rerun."""
    if index.db_dtype != "int8":
        # exact f32 scan over the probed rows — no certificate, hence
        # no margin to note (the scan IS the oracle for its pool)
        return _fine_scan(xs, index.slab, index.ids, index.yy_slab,
                          st, ps, k=k, P=P, W=W)
    C = min(k + _IVF_RESCORE_PAD, P * W)
    vals, ids_c, ok, margin = _fine_scan_q8(
        xs, index.slab, index.slab_q, index.row_scale, index.ids,
        index.yy_q, st, ps, k=k, P=P, W=W, C=C,
        eq_rows=index.eq_rows)
    explain.note_margin("ann.search_ivf_flat", margin)
    n_fail = int(jnp.sum(~ok))
    # quality telemetry: this path ALREADY syncs (the int() above
    # decides the rerun), so the counters cost nothing extra —
    # the IVF slice of the certificate/fixup evidence plane
    record_certificate("ann.search_ivf_flat",
                       n_queries=int(xs.shape[0]), n_fail=n_fail,
                       pool_width=C, fixup_rows=n_fail or None,
                       rerun=bool(n_fail), db_dtype="int8",
                       n_probes=P)
    if n_fail:
        # quantization certificate failed for some queries: the
        # true top-k may extend past the rescored pool — rerun the
        # chunk through the exact f32 scan and keep certified rows
        # from the quantized pass (bytes saved stand; correctness
        # never rides on the margin)
        emit_marker("ivf_q8_fallback", n_fail=n_fail,
                    nq=int(xs.shape[0]))
        explain.note(rerun="q8_exact", rerun_rows=n_fail)
        fv, fi = _fine_scan(xs, index.slab, index.ids,
                            index.yy_slab, st, ps, k=k, P=P, W=W)
        okc = ok[:, None]
        vals = jnp.where(okc, vals, fv)
        ids_c = jnp.where(okc, ids_c, fi)
    return vals, ids_c


def _search_list_major(res, index: IvfFlatIndex, x, probes,
                       probes_host, starts, psizes, k: int, P: int,
                       W: int, chunk: int):
    """The list-major driver: per chunk, invert the probe table into
    the list schedule, run the stream-once kernel, and rerun any
    certificate-failing chunk rows through the query-major scan — the
    returned ids are bit-identical to the query-major oracle either
    way."""
    from raft_tpu.ops.fine_scan_pallas import pad_window

    Wk = pad_window(W)
    host = _list_host(index)
    quant = index.db_dtype == "int8"
    nq = x.shape[0]

    def run_chunk(s0: int, s1: int):
        xs, pr = x[s0:s1], probes[s0:s1]
        st, ps = starts[s0:s1], psizes[s0:s1]
        sched = build_list_schedule(index, probes_host[s0:s1])
        if s0 == 0:
            emit_marker("ivf_fine_scan_schedule", schedule="list",
                        lists_probed=sched.n_lists_probed,
                        q_max=sched.q_max,
                        cells=sched.sched.shape[1] // 8,
                        stream_rows=sched.stream_rows,
                        db_dtype=index.db_dtype)
        if quant:
            vals, ids_c, ok, margin = _fine_scan_list_q8(
                xs, jnp.asarray(sched.sched),
                jnp.asarray(sched.scale_l), pr, index.slab_q,
                index.slab, index.ids, index.yy_slab,
                host["yy_lmax"], host["eq_list"], st, ps,
                k=k, P=P, W=W, Wk=Wk)
        else:
            vals, ids_c, ok, margin = _fine_scan_list(
                xs, jnp.asarray(sched.sched), pr, index.slab,
                index.ids, index.yy_slab, st, ps, host["yy_lmax"],
                k=k, P=P, W=W, Wk=Wk)
        explain.note_margin("ann.search_ivf_flat", margin)
        n_fail = int(jnp.sum(~ok))
        # same host sync the q8 gather path already pays — the
        # list-major slice of the certificate/fixup evidence plane
        record_certificate("ann.search_ivf_flat",
                           n_queries=int(xs.shape[0]), n_fail=n_fail,
                           pool_width=256, fixup_rows=n_fail or None,
                           rerun=bool(n_fail),
                           db_dtype=index.db_dtype, fine_scan="list")
        if n_fail:
            # pool-completeness certificate failed: the true top-k
            # (or one of its ties) may hide outside the 256-slot pool
            # — rerun the chunk query-major and keep certified rows
            emit_marker("ivf_list_fallback", n_fail=n_fail,
                        nq=int(xs.shape[0]))
            explain.note(rerun="list_query_major", rerun_rows=n_fail)
            fv, fi = _query_major_chunk(index, xs, st, ps, k, P, W)
            okc = ok[:, None]
            vals = jnp.where(okc, vals, fv)
            ids_c = jnp.where(okc, ids_c, fi)
        return vals, ids_c

    if nq <= chunk:
        return run_chunk(0, nq)
    outs = [run_chunk(s, min(s + chunk, nq))
            for s in range(0, nq, chunk)]
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


@instrument("ann.search_ivf_flat")
def search_ivf_flat(res, index, queries, k: int,
                    n_probes: Optional[int] = None,
                    merge: str = "auto",
                    fine_scan: Optional[str] = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Approximate top-k against an IVF-Flat index.

    (ref: ivf_flat::search — coarse probe, gather the probed lists,
    list-local select, merge.) Returns (d2 [nq, k] ascending, global
    ids [nq, k]); entries beyond the probed candidates carry
    (+inf, −1) — recall vs the exact oracle is the tracked artifact
    (benchmarks/bench_ann.py → BENCH_ANN.json).

    ``index`` is an :class:`IvfFlatIndex` or a :class:`ShardedIvfIndex`
    (:func:`shard_ivf_lists` — whole lists over the mesh, per-shard
    local top-k + the PR-4 rank-ordered merge picked by ``merge``).

    ``fine_scan`` picks the fine-scan schedule (:data:`FINE_SCANS`;
    ``None`` reads ``RAFT_TPU_IVF_FINE_SCAN``, default ``auto``):
    ``query`` gathers each query's probe windows independently,
    ``list`` streams each probed list ONCE per query chunk for all the
    queries probing it (the ``ops.fine_scan_pallas`` kernels — f32 ids
    certified bit-identical to the query-major scan; int8 id sets
    identical, ties canonicalized to f32 position order), ``auto`` runs
    the :func:`resolve_fine_scan` cost-model crossover on the index's
    actual probed-list histogram. A failing list-major dispatch
    degrades back to query-major with a logged degradation (fault
    site ``fine_scan_list``). The sharded path keeps the query-major
    shard-local scan.

    ``n_probes ≥ n_lists`` (or ``k`` beyond the probed capacity)
    degrades to EXACT search with a logged reason — the certified
    fused pipeline over the ragged slab; the returned id set then
    matches the brute-force oracle exactly (the degenerate-exact
    invariant the tests pin)."""
    fault_point("ivf_search")
    res = ensure_resources(res)
    sharded = isinstance(index, ShardedIvfIndex)
    base = index.base if sharded else index
    x = jnp.asarray(queries, jnp.float32)
    expects(x.ndim == 2 and x.shape[1] == base.d_orig,
            "search_ivf_flat: query width %s != index %d",
            x.shape[1:], base.d_orig)
    expects(k >= 1, "search_ivf_flat: k must be >= 1")
    expects(k <= base.n_rows,
            "search_ivf_flat: k=%d > index size %d", k, base.n_rows)
    nq = x.shape[0]
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    L = base.n_lists
    if n_probes is None:
        # fleet-wide recall knob: RAFT_TPU_ANN_NPROBES retunes every
        # default-probes caller (serving planes included) without a
        # rebuild — read per call, like the pool-select env
        P = _env_int("RAFT_TPU_ANN_NPROBES", base.n_probes_default)
    else:
        P = int(n_probes)
    expects(P >= 1, "search_ivf_flat: n_probes must be >= 1, got %d", P)
    W = index.probe_window
    reason = None
    if P >= L:
        reason = f"n_probes={P} >= n_lists={L}"
    elif k > P * W:
        reason = (f"k={k} exceeds the probed candidate capacity "
                  f"{P}x{W}={P * W}")
    if reason is not None:
        from raft_tpu.core.logger import log_warn

        log_warn("search_ivf_flat: %s — degrading to exact search "
                 "over the full index for this call", reason)
        emit_marker("ivf_exact_degrade", reason=reason, k=k,
                    n_probes=P, n_lists=L)
        explain.note(plane="ivf_flat", exact_degrade=reason,
                     n_probes=P, n_lists=L, k=k)
        return _exact_search(res, base, x, k)

    probes = _coarse_probe(res, base.centroids, x, P)       # [nq, P]

    if explain.active() is not None:
        # explain capture: probed list ids (first query's probe set —
        # the record is per-request-batch) + the probed-size histogram
        # and pool width; the host transfer only happens under capture
        pr_np = np.asarray(probes)
        sz = np.asarray(base.sizes)[pr_np]
        explain.note(plane="ivf_flat", n_probes=P, n_lists=L, k=k,
                     db_dtype=base.db_dtype,
                     probed_lists=pr_np[0].tolist(),
                     probed_rows=int(sz.sum()),
                     probed_size_hist={
                         "min": int(sz.min()), "p50": float(
                             np.percentile(sz, 50)),
                         "max": int(sz.max())},
                     pool_width=(min(k + _IVF_RESCORE_PAD,
                                     P * index.probe_window)
                                 if base.db_dtype == "int8" else k))

    rec = get_flight_recorder()
    if rec.enabled:
        probed_rows = float(jnp.sum(jnp.take(base.sizes, probes)))
        emit_marker("ivf_search", nq=nq, k=k, n_probes=P, n_lists=L,
                    probed_frac=round(
                        probed_rows / max(1, nq * base.n_rows), 6),
                    sharded=bool(sharded))

    if sharded:
        return _search_sharded(res, index, x, probes, k, P, W, merge)

    starts = jnp.take(index.offsets[:-1], probes)
    psizes = jnp.take(index.padded_sizes, probes)
    d = x.shape[1]
    chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
    try:
        res.profiler.capture_fn(
            "ann.ivf_fine_scan", _fine_scan,
            x[:min(nq, chunk)], index.slab, index.ids, index.yy_slab,
            starts[:min(nq, chunk)], psizes[:min(nq, chunk)],
            k=k, P=P, W=W)
    except Exception:
        pass

    # fine-scan schedule: env/arg request resolved against the
    # list-major envelope + the cost-model crossover on the ACTUAL
    # probe table (resolve_fine_scan). A list-major failure — real or
    # injected at the fine_scan_list site — degrades back to the
    # query-major scan for this call, with identical ids.
    req = fine_scan if fine_scan is not None \
        else env.get("RAFT_TPU_IVF_FINE_SCAN")
    probes_host = np.asarray(probes) if req != "query" else None
    schedule = resolve_fine_scan(index, nq, k, P, W, req,
                                 probes_np=probes_host, chunk=chunk)
    explain.note(fine_scan=schedule)
    if schedule == "list":
        try:
            fault_point("fine_scan_list")
            return _search_list_major(res, index, x, probes,
                                      probes_host, starts, psizes,
                                      k, P, W, chunk)
        except DeadlineExceededError:
            raise               # the caller's global budget — never eaten
        except Exception as e:
            from raft_tpu.core.logger import log_warn

            record_degradation("fine_scan_list", "query")
            emit_marker("fine_scan_degrade",
                        reason=f"{type(e).__name__}: {e}"[:160])
            explain.note(fine_scan_degrade=f"{type(e).__name__}"[:64])
            log_warn("list-major fine scan failed (%s: %s) — "
                     "degrading to the query-major scan for this "
                     "call", type(e).__name__, e)

    if nq <= chunk:
        return _query_major_chunk(index, x, starts, psizes, k, P, W)
    outs = [_query_major_chunk(index, x[s:s + chunk],
                               starts[s:s + chunk],
                               psizes[s:s + chunk], k, P, W)
            for s in range(0, nq, chunk)]
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


# ----------------------------------------------------------- sharded
class ShardedIvfIndex:
    """Whole inverted lists distributed over a mesh axis (the
    ``shard="lists"`` layout): shard ``r`` owns the contiguous list
    block [r·Ll, (r+1)·Ll) laid out in its own local slab; list→shard
    routing is pure arithmetic. Build with :func:`shard_ivf_lists`;
    query through :func:`search_ivf_flat` (type-dispatched)."""

    def __init__(self, base: IvfFlatIndex, mesh, axis: str,
                 slab_s, ids_s, yy_s, starts_g, psizes_g,
                 lists_per: int, rows_per: int, slab_qs=None,
                 scale_s=None, yyq_s=None, eq_s=None):
        self.base = base
        self.mesh, self.axis = mesh, axis
        self.slab_s = slab_s        # [p·rows_per, d] sharded P(axis)
        self.ids_s = ids_s          # [p·rows_per] global ids, -1 pads
        self.yy_s = yy_s            # [p·rows_per] row norms
        self.starts_g = starts_g    # [Lg] LOCAL start row per list
        self.psizes_g = psizes_g    # [Lg] padded sizes (0 = empty)
        self.lists_per = lists_per
        self.rows_per = rows_per
        # int8 sidecar, sharded in the same block layout as the f32
        # slab (PR-9 parity gap closed: the shard-local fine scan
        # streams the quantized rows, certifies, and exact-rescoring
        # rides the f32 slab that is already resident per shard)
        self.slab_qs = slab_qs      # [p·rows_per, d] int8 or None
        self.scale_s = scale_s      # [p·rows_per] f32 per-row scale
        self.yyq_s = yyq_s          # [p·rows_per] ‖ŷ‖²
        self.eq_s = eq_s            # [p·rows_per] per-row Eq bound

    @property
    def n_shards(self) -> int:
        return int(self.mesh.shape[self.axis])

    @property
    def probe_window(self) -> int:
        return self.base.probe_window


def shard_ivf_lists(index: IvfFlatIndex, mesh, axis: str = "x"
                    ) -> ShardedIvfIndex:
    """Lay an :class:`IvfFlatIndex` out list-sharded over
    ``mesh[axis]``: lists pad to ``p`` equal blocks (virtual empty
    lists), every shard's local slab pads to the max shard row count
    (shard_map needs equal shards), and the shards land via ONE
    sharded ``device_put`` — the slab never materializes replicated on
    any device. Global ids ride inside each local slab, so the merged
    results need no offset arithmetic."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    expects(axis in mesh.axis_names,
            "shard_ivf_lists: axis %r not in mesh axes %s", axis,
            tuple(mesh.axis_names))
    p = int(mesh.shape[axis])
    L = index.n_lists
    Lg = -(-L // p) * p
    Ll = Lg // p
    offsets, padded = index._np_offsets, index._np_padded
    slab = np.asarray(index.slab)
    ids = np.asarray(index.ids)
    # yy is GATHERED from the base index, not recomputed — the sharded
    # and unsharded fine scans must score bitwise-identical d2 per
    # candidate, and a host-side re-summation could round differently
    yy = np.asarray(index.yy_slab)
    d = slab.shape[1]
    # per-shard row counts (sum of its lists' padded sizes)
    shard_rows = [int(padded[r * Ll:min((r + 1) * Ll, L)].sum())
                  for r in range(p)]
    S = max(max(shard_rows), index.row_quantum)
    slab_g = np.zeros((p * S, d), np.float32)
    ids_g = np.full(p * S, -1, np.int32)
    yy_g = np.zeros(p * S, np.float32)
    starts_g = np.zeros(Lg, np.int32)
    psizes_g = np.zeros(Lg, np.int32)
    psizes_g[:L] = padded
    for r in range(p):
        cursor = 0
        for gl in range(r * Ll, min((r + 1) * Ll, L)):
            w = int(padded[gl])
            starts_g[gl] = cursor
            if w:
                src = int(offsets[gl])
                dst = r * S + cursor
                slab_g[dst:dst + w] = slab[src:src + w]
                ids_g[dst:dst + w] = ids[src:src + w]
                yy_g[dst:dst + w] = yy[src:src + w]
            cursor += w
    q8_kw = {}
    if index.db_dtype == "int8":
        # the PR-9 sidecar, laid out in the SAME per-shard block
        # geometry (gathered from the base arrays, not recomputed —
        # the sharded and unsharded quantized scans must score the
        # same ŷ bit-for-bit)
        slab_q = np.asarray(index.slab_q)
        scale = np.asarray(index.row_scale)
        yyq = np.asarray(index.yy_q)
        eqr = np.asarray(index.eq_rows)
        slab_qg = np.zeros((p * S, d), np.int8)
        scale_g = np.ones(p * S, np.float32)
        yyq_g = np.zeros(p * S, np.float32)
        eq_g = np.zeros(p * S, np.float32)
        for r in range(p):
            cursor = 0
            for gl in range(r * Ll, min((r + 1) * Ll, L)):
                w = int(padded[gl])
                if w:
                    src = int(offsets[gl])
                    dst = r * S + cursor
                    slab_qg[dst:dst + w] = slab_q[src:src + w]
                    scale_g[dst:dst + w] = scale[src:src + w]
                    yyq_g[dst:dst + w] = yyq[src:src + w]
                    eq_g[dst:dst + w] = eqr[src:src + w]
                cursor += w
        q8_kw = dict(slab_qs=slab_qg, scale_s=scale_g, yyq_s=yyq_g,
                     eq_s=eq_g)
    sh = NamedSharding(mesh, P(axis))
    return ShardedIvfIndex(
        index, mesh, axis,
        slab_s=jax.device_put(slab_g, sh),
        ids_s=jax.device_put(ids_g, sh),
        yy_s=jax.device_put(yy_g, sh),
        starts_g=jnp.asarray(starts_g),
        psizes_g=jnp.asarray(psizes_g),
        lists_per=Ll, rows_per=S,
        **{key: jax.device_put(val, sh)
           for key, val in q8_kw.items()})


def _search_sharded(res, index: ShardedIvfIndex, x, probes, k: int,
                    P: int, W: int, merge: str):
    """List-sharded fine scan + rank-ordered merge. Every shard scans
    the probed lists IT owns (unowned probes masked), selects its local
    top-k with global ids, and the per-shard candidates merge with the
    PR-4 machinery — deterministic rank-major pools, so the result is
    replicated bit-for-bit and matches the unsharded scan's id set."""
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    from raft_tpu.comms import MeshComms
    from raft_tpu.distance.knn_sharded import (_merge_allgather,
                                               _merge_tournament,
                                               resolve_merge_strategy)
    from raft_tpu.parallel import replicated

    mesh, axis = index.mesh, index.axis
    p = index.n_shards
    expects(merge in ("auto", "allgather", "tournament"),
            "search_ivf_flat: merge must be 'auto', 'allgather' or "
            "'tournament', got %r", merge)
    nq = x.shape[0]
    merge_eff = resolve_merge_strategy(merge, p, nq, k)
    if merge_eff == "host":     # not a rung here — auto never picks it
        merge_eff = "allgather"
    # fault sites fire in the WRAPPER (per call), like knn_sharded's
    # resilience driver — a trace-time site inside shard_map would fire
    # once per compile and lie for every cached dispatch after
    if merge_eff == "tournament":
        fault_point("merge_permute")
    else:
        fault_point("merge_allgather")
    d = x.shape[1]
    chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
    if nq > chunk:
        outs = [_search_sharded(res, index, x[s:s + chunk],
                                probes[s:s + chunk], k, P, W, merge)
                for s in range(0, nq, chunk)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))

    Ll, S = index.lists_per, index.rows_per
    quant = index.base.db_dtype == "int8" and index.slab_qs is not None
    repl = replicated(mesh)
    common = (jax.device_put(x, repl), jax.device_put(probes, repl),
              jax.device_put(index.starts_g, repl),
              jax.device_put(index.psizes_g, repl))

    def _f32_fn():
        key = (mesh, axis, k, P, W, S, Ll, merge_eff, d, nq, "f32")
        fn = _SHARDED_IVF_CACHE.get(key)
        if fn is None:
            comms = MeshComms(axis, size=p)
            merge_fn = {"allgather": _merge_allgather,
                        "tournament": _merge_tournament}[merge_eff]

            def shard_fn(slab_l, ids_l, yy_l, xq, pr, starts_g, psz_g):
                r = jax.lax.axis_index(axis).astype(jnp.int32)
                owned = (pr >= r * Ll) & (pr < (r + 1) * Ll)
                starts = jnp.take(starts_g, pr)
                psz = jnp.where(owned, jnp.take(psz_g, pr), 0)
                vals, gids = _fine_scan(xq, slab_l, ids_l, yy_l,
                                        starts, psz, k=k, P=P, W=W)
                return merge_fn(comms, p, k, vals, gids)

            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=mesh,
                in_specs=(Pspec(axis), Pspec(axis), Pspec(axis),
                          Pspec(), Pspec(), Pspec(), Pspec()),
                out_specs=(Pspec(), Pspec()), check_vma=False))
            _SHARDED_IVF_CACHE[key] = fn
        return fn

    if not quant:
        return _f32_fn()(index.slab_s, index.ids_s, index.yy_s,
                         *common)

    # int8 shard-local fine scan (the PR-9 sharded parity gap): each
    # shard streams ITS quantized rows (~¼ the probed bytes), prunes
    # to the certified pool, exact-rescoring from its resident f32
    # slab — certificates come out per shard ([p, nq] over the axis),
    # and any query a shard could not certify reruns the whole chunk
    # through the f32 program, so merged ids never degrade.
    C = min(k + _IVF_RESCORE_PAD, P * W)
    key = (mesh, axis, k, P, W, S, Ll, merge_eff, d, nq, "int8")
    fn = _SHARDED_IVF_CACHE.get(key)
    if fn is None:
        comms = MeshComms(axis, size=p)
        merge_fn = {"allgather": _merge_allgather,
                    "tournament": _merge_tournament}[merge_eff]

        def shard_fn_q8(slab_l, slabq_l, scale_l, yyq_l, eq_l, ids_l,
                        xq, pr, starts_g, psz_g):
            r = jax.lax.axis_index(axis).astype(jnp.int32)
            owned = (pr >= r * Ll) & (pr < (r + 1) * Ll)
            starts = jnp.take(starts_g, pr)
            psz = jnp.where(owned, jnp.take(psz_g, pr), 0)
            # margin (4th output) is DCE'd — per-shard margins would
            # need their own out_spec the explain plane doesn't ask for
            vals, gids, ok, _ = _fine_scan_q8(
                xq, slab_l, slabq_l, scale_l, ids_l, yyq_l, starts,
                psz, k=k, P=P, W=W, C=C, eq_rows=eq_l)
            mv, mi = merge_fn(comms, p, k, vals, gids)
            return mv, mi, ok[None, :]

        fn = jax.jit(jax.shard_map(
            shard_fn_q8, mesh=mesh,
            in_specs=(Pspec(axis),) * 6
            + (Pspec(), Pspec(), Pspec(), Pspec()),
            out_specs=(Pspec(), Pspec(), Pspec(axis)),
            check_vma=False))
        _SHARDED_IVF_CACHE[key] = fn

    vals, gids, ok_p = fn(index.slab_s, index.slab_qs, index.scale_s,
                          index.yyq_s, index.eq_s, index.ids_s,
                          *common)
    ok = np.asarray(ok_p).all(axis=0)                       # [nq]
    n_fail = int((~ok).sum())
    record_certificate("ann.search_ivf_flat", n_queries=nq,
                       n_fail=n_fail, pool_width=C,
                       fixup_rows=n_fail or None, rerun=bool(n_fail),
                       db_dtype="int8", sharded=True)
    if n_fail:
        emit_marker("ivf_q8_fallback", n_fail=n_fail, nq=nq,
                    sharded=True)
        fv, fi = _f32_fn()(index.slab_s, index.ids_s, index.yy_s,
                           *common)
        okd = jnp.asarray(ok)[:, None]
        vals = jnp.where(okd, vals, fv)
        gids = jnp.where(okd, gids, fi)
    return vals, gids
