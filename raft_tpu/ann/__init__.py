"""raft_tpu.ann — approximate nearest neighbors (the IVF tier).

(ref: the reference's historical headline capability — the ANN stack
(ivf_flat.cuh / ivf_flat_types.hpp, neighbors/detail/ivf_flat_*) that
migrated to cuVS. Brute force at the 2048×10M×256 north star is
permanently HBM-bandwidth-bound; the only way past the streamed-HBM
wall is to read LESS of the database per query. IVF-Flat is the first
rung: a balanced k-means coarse quantizer (raft_tpu.cluster) buckets
the database into inverted lists, a query probes ``n_probes`` of them,
and recall@k vs the bit-exact brute-force oracle becomes a tracked
artifact next to GB/s (BENCH_ANN.json). IVF-PQ (ivf_pq.cuh lineage)
is the compressed rung on top: per-subspace product-quantized codes
cut the streamed bytes ~16–32× behind a certified exact f32 rescore,
so 100M-class databases fit one chip's HBM budget.)
"""

from raft_tpu.ann.ivf_flat import (DEFAULT_ROW_QUANTUM, FINE_SCANS,
                                   IvfFlatIndex, ShardedIvfIndex,
                                   build_ivf_flat, build_list_schedule,
                                   resolve_fine_scan, search_ivf_flat,
                                   shard_ivf_lists, warm_fine_scan)
from raft_tpu.ann.ivf_pq import (PQ_SCANS, IvfPqIndex, build_ivf_pq,
                                 pack_pq_codes, resolve_pq_scan,
                                 search_ivf_pq, unpack_pq_codes,
                                 warm_pq_scan)

__all__ = [
    "DEFAULT_ROW_QUANTUM",
    "FINE_SCANS",
    "PQ_SCANS",
    "IvfFlatIndex",
    "IvfPqIndex",
    "ShardedIvfIndex",
    "build_ivf_flat",
    "build_ivf_pq",
    "build_list_schedule",
    "pack_pq_codes",
    "resolve_fine_scan",
    "resolve_pq_scan",
    "search_ivf_flat",
    "search_ivf_pq",
    "shard_ivf_lists",
    "unpack_pq_codes",
    "warm_fine_scan",
    "warm_pq_scan",
]
