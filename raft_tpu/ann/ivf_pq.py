"""IVF-PQ: the product-quantized compressed tier over the IVF slab.

(ref: neighbors/ivf_pq.cuh — the reference ecosystem's flagship
billion-vector index, migrated to cuVS as ``ivf_pq::build/search`` +
its ``refine`` step. The int8 slab (PR 9) halves database bytes and
the list-major fine scan (PR 14) kills the gather overread; product
quantization is the ~16–32× rung: serving 100M–1B vectors from one
chip's HBM means the scanned representation must shrink past what any
scalar quantizer gives.)

Index (:class:`IvfPqIndex`, built by :func:`build_ivf_pq`): the PR-8
IVF-Flat padded ragged slab UNCHANGED (coarse balanced k-means, f32
slab retained — it is the mandatory exact-rescore plane), plus the
compressed sidecar packed into the same
:class:`~raft_tpu.mutable.layout.IndexLayout` geometry:

- ``pq_dim`` subspaces of width ``d / pq_dim``; per-subspace codebooks
  of ``2^pq_bits`` codewords trained with the PR-8
  :func:`~raft_tpu.cluster.kmeans_fit` on RESIDUALS to the coarse
  centroid (the cuVS ``by_residual`` shape);
- a codes slab ``[R, pq_dim]`` (8-bit, stored biased) or
  ``[R, pq_dim/2]`` (4-bit, two codes per byte) laid out row-for-row
  with the f32 slab, plus the 4-byte reconstructed-norm sidecar
  ``‖ŷ‖²`` — the ONLY bytes the compressed scan streams;
- per-subspace quantization-error bounds recorded at build
  (generalizing the PR-9 per-group ``Eq`` argument: ``pq_eq_sub[s]``
  envelopes every encoded row's subspace residual norm, and the
  per-row/per-list roll-ups widen the completeness certificate).

Search (:func:`search_ivf_pq`): coarse probe → the PR-14 list-major
schedule (``build_list_schedule`` reused verbatim) → the
:func:`~raft_tpu.ops.pq_scan_pallas.pq_scan_list_major` ADC kernel —
per-query ``[pq_dim, 2^pq_bits]`` lookup tables computed on entry and
held VMEM-resident while code blocks stream through the 2-slot DMA
pipeline — → pooled candidates MANDATORILY exact-rescored from the
f32 slab under a PER-QUERY ADAPTIVE completeness certificate: the
kernel folds each streamed row's certified true-distance lower bound
``(max(√d2_adc − Eq_row, 0))²`` (the recorded per-row round-trip
error, streamed as a 4-byte sidecar), so the pooled rest-min is
compared against ``θ`` plus only the kernel-precision envelope — no
per-list worst-case ``Eq`` widening. Certificate failures climb a
three-rung ladder: (1) certified as-is, (2) the ``pq_widen`` rung
re-runs the ADC scan with a 2×/4× deeper candidate pool and
re-certifies, (3) the exact f32 rerun. The ``pq_scan`` fault site
degrades any kernel failure to the f32/int8 query-major scan — so
returned id sets NEVER degrade below the flat scan's, whatever the
compression does to the approximate scores.

``pq_mode`` picks the quantizer: ``"plain"`` trains codebooks on raw
residuals; ``"opq"`` learns an orthogonal rotation first (OPQ
alternating minimization — orthogonal Procrustes against the current
reconstruction, codebooks re-trained on the rotated residuals — ref:
Ge et al., and cuVS' codebook options); ``"opq_aniso"`` additionally
assigns codewords under a score-aware anisotropic loss (ScaNN-style:
the residual component parallel to the data point is weighted η×).
The rotation is stored as ``pq_rot`` (also on the shared
``IndexLayout``), applied to QUERIES at ADC-table build and to
RESIDUALS at encode — norms are preserved, so every certificate and
sidecar stays exactly as recorded.

``n_probes ≥ n_lists`` (or ``k`` past the probed capacity) degrades
to certified-exact search over the f32 slab exactly like IVF-Flat —
:class:`IvfPqIndex` IS an :class:`~raft_tpu.ann.ivf_flat.IvfFlatIndex`
and inherits the whole degenerate/exact/layout machinery.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import env
from raft_tpu.core.error import DeadlineExceededError, expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import explain, instrument
from raft_tpu.observability.quality import (record_certificate,
                                            record_pq_rungs)
from raft_tpu.observability.timeline import emit_marker
from raft_tpu.resilience import fault_point
from raft_tpu.resilience.policy import record_degradation

from raft_tpu.ann.ivf_flat import (_FINE_TILE, _LIST_K_MAX,
                                   _coarse_probe, _exact_search,
                                   _fine_scan, _list_host,
                                   _pad_kernel_operands,
                                   _query_major_chunk, IvfFlatIndex,
                                   build_ivf_flat, build_list_schedule)

#: PQ schedule choices: "pq" = the list-major ADC kernel over the
#: codes slab, "flat" = the uncompressed IVF-Flat fine scan (query- or
#: list-major per its own chooser), "auto" = the resolve_pq_scan
#: cost-model crossover. Env: RAFT_TPU_IVF_PQ_SCAN.
PQ_SCANS = ("auto", "pq", "flat")

#: quantizer modes: "plain" = codebooks on raw residuals, "opq" = the
#: learned orthogonal rotation (OPQ alternating minimization),
#: "opq_aniso" = OPQ + score-aware anisotropic codeword assignment.
#: Env default: RAFT_TPU_ANN_PQ_MODE.
PQ_MODES = ("plain", "opq", "opq_aniso")

#: anisotropic assignment weight: the residual component PARALLEL to
#: the data point costs this much more than the orthogonal one
#: (ScaNN's score-aware loss, fixed-η form)
_PQ_ANISO_ETA = 4.0

#: multiplicative headroom on every recorded f32 error bound — covers
#: the f32 norm/summation rounding between the recorded bound and the
#: true (f64) round-trip error, same spirit as the PR-9 _Q8_ERR slack
_PQ_EQ_HEADROOM = 1.0 + 2.0 ** -10
#: additive headroom, scaled by the row/subspace magnitude: a row
#: whose residual is EXACTLY a codeword records an f32 error of 0
#: while the true round-trip still carries the f32 representation
#: error of the reconstruction arithmetic (~ULPs of the magnitudes
#: involved) — the relative term alone cannot cover a zero
_PQ_EQ_ABS = 2.0 ** -16


def _default_pq_dim(d: int) -> int:
    """Largest divisor of ``d`` not exceeding ``d // 4`` — the 4-byte-
    per-subspace default (~16× at 8-bit codes) that still tiles the
    feature width exactly."""
    target = max(1, d // 4)
    for cand in range(target, 0, -1):
        if d % cand == 0:
            return cand
    return 1


def pack_pq_codes(codes, pq_bits: int):
    """Host-side code packing: 8-bit codes store BIASED (code − 128)
    int8 so the full 0..255 range fits; 4-bit codes pack two per byte
    (low nibble = even subspace). Mirrors the kernel's
    ``_decode_subspaces``."""
    codes = np.asarray(codes, np.int64)
    if pq_bits == 8:
        return (codes - 128).astype(np.int8)
    expects(codes.shape[1] % 2 == 0,
            "pack_pq_codes: 4-bit packing needs an even pq_dim")
    low = codes[:, 0::2]
    high = codes[:, 1::2]
    return (low | (high << 4)).astype(np.uint8).view(np.int8)


def unpack_pq_codes(packed, pq_dim: int, pq_bits: int):
    """Inverse of :func:`pack_pq_codes` (tests / the mutable plane)."""
    packed = np.asarray(packed)
    if pq_bits == 8:
        return packed.astype(np.int64) + 128
    vu = packed.view(np.uint8).astype(np.int64)
    out = np.empty((packed.shape[0], pq_dim), np.int64)
    out[:, 0::2] = vu % 16
    out[:, 1::2] = vu // 16
    return out


class IvfPqIndex(IvfFlatIndex):
    """IVF-Flat slab + the product-quantized sidecar. Inherits every
    flat plane (degenerate-exact search, layout, schedule builder,
    sharding geometry); adds the codebooks, the packed codes slab, the
    reconstructed norms and the recorded error bounds."""

    def __init__(self, *args, pq_dim: int = 0, pq_bits: int = 8,
                 codebooks=None, codes=None, yy_pq=None,
                 pq_eq_rows=None, pq_eq_sub=None, pq_eq_list=None,
                 pq_rhat_list=None, pq_mode: str = "plain",
                 pq_rot=None, pq_eq_qlist=None,
                 pq_resid_med: float = 0.0, **kw):
        super().__init__(*args, **kw)
        self.pq_dim = int(pq_dim)            # subspace count S
        self.pq_bits = int(pq_bits)          # 4 or 8
        self.codebooks = codebooks           # [S, K, dsub] f32
        self.codes = codes                   # [R, S or S/2] int8 packed
        self.yy_pq = yy_pq                   # [R, 1] f32 ‖ŷ‖² (pads 0)
        self.pq_eq_rows = pq_eq_rows         # [R] f32 ‖y − ŷ‖ bound
        self.pq_eq_sub = pq_eq_sub           # [S] f32 subspace envelope
        self.pq_eq_list = pq_eq_list         # [L] f32 per-list max
        self.pq_rhat_list = pq_rhat_list     # [L] f32 max ‖r̂‖ per list
        self.pq_mode = str(pq_mode)          # plain | opq | opq_aniso
        self.pq_rot = pq_rot                 # [d, d] f32 or None
        self.pq_eq_qlist = pq_eq_qlist       # [L, 3] q50/q90/max sketch
        self.pq_resid_med = float(pq_resid_med)  # median ‖y − c‖
        self._pq_eq_col = None               # lazy [R, 1] kernel view

    @property
    def dsub(self) -> int:
        return self.d_orig // self.pq_dim

    @property
    def pq_k(self) -> int:
        return 1 << self.pq_bits

    @property
    def code_bytes(self) -> int:
        """Streamed code bytes per row."""
        return self.pq_dim if self.pq_bits == 8 else self.pq_dim // 2

    @property
    def pq_eq_col(self):
        """[R, 1] device view of ``pq_eq_rows`` — the adaptive-
        certificate sidecar the ADC kernel streams (built once)."""
        if self._pq_eq_col is None:
            self._pq_eq_col = jnp.reshape(
                jnp.asarray(self.pq_eq_rows, jnp.float32), (-1, 1))
        return self._pq_eq_col

    def __repr__(self):
        return (f"IvfPqIndex(n_rows={self.n_rows}, "
                f"n_lists={self.n_lists}, d={self.d_orig}, "
                f"pq_dim={self.pq_dim}, pq_bits={self.pq_bits}, "
                f"window={self.probe_window})")

    def layout(self):
        """The shared :class:`~raft_tpu.mutable.layout.IndexLayout`
        with the PQ sidecar packed alongside the f32 slab — the codes
        ride the same padded-ragged geometry every plane shares."""
        lay = super().layout()
        lay.pq_codes = self.codes
        lay.pq_yy = self.yy_pq
        lay.pq_eq_rows = self.pq_eq_rows
        lay.pq_rot = self.pq_rot
        lay.pq_meta = {"pq_dim": self.pq_dim, "pq_bits": self.pq_bits,
                       "pq_mode": self.pq_mode,
                       "codebooks": self.codebooks}
        return lay


def _opq_rotation(res, train, S: int, dsub: int, K: int, seed: int,
                  n_iters: int = 3, train_iters: int = 3):
    """OPQ alternating minimization over the residual TRAIN sample:
    (codebooks | rotation) → encode → orthogonal Procrustes (the SVD
    of ``trainᵀ · recon`` — min ‖train·R − recon‖ over orthogonal R)
    → re-train codebooks on the re-rotated residuals, warm-started via
    ``kmeans_fit(init_centroids=…)``. Returns ``(R [d,d] f32, warm
    per-subspace codebooks)`` — the caller runs the final full-budget
    codebook train on ``train @ R`` seeded with the warm books.
    Orthogonality is exact to f32 rounding (the SVD runs in f64)."""
    from raft_tpu.cluster import kmeans_fit, kmeans_predict

    d = train.shape[1]
    rot = np.eye(d, dtype=np.float32)
    cbs = [None] * S
    for _ in range(max(1, int(n_iters))):
        tr = (train @ rot).astype(np.float32)
        recon = np.empty_like(tr)
        for s in range(S):
            sl = slice(s * dsub, (s + 1) * dsub)
            km = kmeans_fit(res, tr[:, sl], K, max_iter=train_iters,
                            seed=seed + 211 + s, balanced=False,
                            init_centroids=cbs[s])
            cbs[s] = np.asarray(km.centroids, np.float32)
            code = np.asarray(kmeans_predict(res, km.centroids,
                                             tr[:, sl]))
            recon[:, sl] = cbs[s][code]
        u, _, vt = np.linalg.svd(
            train.astype(np.float64).T @ recon.astype(np.float64))
        rot = (u @ vt).astype(np.float32)
    return rot, cbs


def _aniso_assign(sub, cb, eta: float = _PQ_ANISO_ETA):
    """Score-aware codeword assignment for one subspace (ScaNN's
    anisotropic loss, fixed-η form): pick ``argmin_c ‖r − c‖² +
    (η − 1)·((r − c)·r/‖r‖)²`` — quantization error PARALLEL to the
    residual (which perturbs the dot-product score directly) costs η×
    the orthogonal error. Codebook centroids stay the k-means fit;
    only the assignment is re-weighted. Chunked [rows × K] host
    sweep."""
    sub = np.asarray(sub, np.float32)
    cb = np.asarray(cb, np.float32)
    n = sub.shape[0]
    out = np.empty(n, np.int32)
    cc = np.sum(cb * cb, axis=1)
    step = 65536
    for s0 in range(0, n, step):
        r = sub[s0:s0 + step]
        rn2 = np.sum(r * r, axis=1, keepdims=True)       # [n, 1]
        rn = np.sqrt(rn2)
        rc = r @ cb.T                                    # [n, K]
        base = rn2 + cc[None, :] - 2.0 * rc
        par = (rn - rc / np.maximum(rn, 1e-30)) ** 2
        par = np.where(rn > 0.0, par, 0.0)
        out[s0:s0 + step] = np.argmin(base + (eta - 1.0) * par,
                                      axis=1)
    return out


@instrument("ann.build_ivf_pq")
def build_ivf_pq(res, y, n_lists: int, pq_dim: Optional[int] = None,
                 pq_bits: Optional[int] = None,
                 n_probes: Optional[int] = None, max_iter: int = 10,
                 pq_max_iter: int = 8, seed: int = 0,
                 balanced: bool = True,
                 row_quantum: Optional[int] = None,
                 max_train_rows: Optional[int] = None,
                 pq_train_rows: Optional[int] = None,
                 pq_mode: Optional[str] = None,
                 opq_iters: int = 3) -> IvfPqIndex:
    """Build an :class:`IvfPqIndex` over ``y`` [m, d].

    (ref: ivf_pq::build — coarse train, per-subspace codebooks on
    residuals, encode.) The coarse stage IS :func:`~raft_tpu.ann.
    build_ivf_flat` (balanced k-means + the padded ragged slab; the
    f32 slab stays resident as the exact-rescore plane). Then, per
    subspace ``s`` of width ``d / pq_dim``:

    1. a ``2^pq_bits``-codeword codebook is trained with the PR-8
       :func:`~raft_tpu.cluster.kmeans_fit` on a ≤ ``pq_train_rows``
       sub-sample of the RESIDUALS ``y − c_assigned`` (default cap
       ``max(32·2^pq_bits, 4096)``);
    2. every slab row's residual subvector is assigned to its nearest
       codeword (the fusedL2NN argmin sweep) → the packed codes slab;
    3. the recorded error bounds: ``pq_eq_sub[s]`` = the max subspace
       round-trip ``‖resid_s − cb_s[code]‖`` over the encoded rows
       (× the ``(1 + 2⁻¹⁰)`` f32 headroom — the envelope the property
       tests attack), ``pq_eq_rows`` the exact per-row ``‖y − ŷ‖``
       and ``pq_eq_list`` its per-list max (the certificate inputs).

    ``pq_mode`` ∈ :data:`PQ_MODES` (default the
    ``RAFT_TPU_ANN_PQ_MODE`` knob): ``"opq"`` learns an orthogonal
    rotation by alternating minimization before the codebook train
    (applied to residuals at encode and to queries at ADC-table
    build); ``"opq_aniso"`` additionally assigns codewords under the
    score-aware anisotropic loss. ``pq_bits`` defaults to
    ``RAFT_TPU_ANN_PQ_BITS`` (8). Carries the ``pq_train`` and
    ``opq_train`` fault sites — a failing codebook/rotation train must
    surface at build, never as a silently-flat index."""
    from raft_tpu.cluster import kmeans_fit, kmeans_predict

    res = ensure_resources(res)
    y = np.asarray(y, np.float32)
    m, d = y.shape
    if pq_mode is None:
        pq_mode = env.get("RAFT_TPU_ANN_PQ_MODE")
    expects(pq_mode in PQ_MODES,
            "build_ivf_pq: pq_mode must be one of %s, got %r",
            PQ_MODES, pq_mode)
    if pq_bits is None:
        pq_bits = env.get("RAFT_TPU_ANN_PQ_BITS")
    pq_bits = int(pq_bits)
    expects(pq_bits in (4, 8),
            "build_ivf_pq: pq_bits must be 4 or 8, got %d", pq_bits)
    S = int(pq_dim) if pq_dim else _default_pq_dim(d)
    expects(S >= 1 and d % S == 0,
            "build_ivf_pq: pq_dim=%d must divide d=%d", S, d)
    expects(pq_bits == 8 or S % 2 == 0,
            "build_ivf_pq: 4-bit codes pack two per byte — pq_dim=%d "
            "must be even", S)
    K = 1 << pq_bits
    expects(m >= K,
            "build_ivf_pq: %d rows < 2^pq_bits = %d codewords — "
            "shrink pq_bits or use IVF-Flat", m, K)
    dsub = d // S

    flat = build_ivf_flat(res, y, n_lists=n_lists, n_probes=n_probes,
                          max_iter=max_iter, seed=seed,
                          balanced=balanced, row_quantum=row_quantum,
                          max_train_rows=max_train_rows)
    L = flat.n_lists
    padded = np.asarray(flat.padded_sizes)
    gid = np.repeat(np.arange(L, dtype=np.int32), padded)
    slab = np.asarray(flat.slab)
    ids = np.asarray(flat.ids)
    valid = ids >= 0
    cents = np.asarray(flat.centroids)
    resid = slab - cents[gid]                       # [R, d] residuals
    R = slab.shape[0]

    # --- per-subspace codebooks on the residual sub-sample ------------
    fault_point("pq_train")
    n_valid = int(valid.sum())
    cap = pq_train_rows or max(32 * K, 4096)
    vrows = np.nonzero(valid)[0]
    if n_valid > cap:
        rng = np.random.default_rng(seed + 17)
        vrows = rng.choice(vrows, cap, replace=False)
    train = resid[vrows]
    expects(train.shape[0] >= K,
            "build_ivf_pq: %d valid rows < %d codewords", n_valid, K)
    rot = None
    warm_cb = [None] * S
    if pq_mode != "plain":
        # the learned rotation: OPQ alternating minimization over the
        # train sample, then the full-budget codebook train below runs
        # in the ROTATED residual space (warm-started from the OPQ
        # books)
        fault_point("opq_train")
        rot, warm_cb = _opq_rotation(res, train, S, dsub, K, seed,
                                     n_iters=opq_iters,
                                     train_iters=max(
                                         1, pq_max_iter // 2))
        train = (train @ rot).astype(np.float32)
        resid_enc = (resid @ rot).astype(np.float32)
    else:
        resid_enc = resid
    codebooks = np.zeros((S, K, dsub), np.float32)
    codes = np.zeros((R, S), np.int32)
    for s in range(S):
        sub = train[:, s * dsub:(s + 1) * dsub]
        km = kmeans_fit(res, sub, K, max_iter=pq_max_iter,
                        seed=seed + 101 + s, balanced=False,
                        init_centroids=warm_cb[s])
        codebooks[s] = np.asarray(km.centroids)
        sub_all = resid_enc[:, s * dsub:(s + 1) * dsub]
        if pq_mode == "opq_aniso":
            codes[:, s] = _aniso_assign(sub_all, codebooks[s])
        else:
            codes[:, s] = np.asarray(kmeans_predict(
                res, km.centroids, sub_all))

    # --- reconstruction + the recorded error envelopes ----------------
    # (with a rotation: codes encode the ROTATED residual r' = r·R, so
    # the reconstructed row is c + r̂'·Rᵀ — norms preserved, every
    # envelope below is computed on the ACTUAL reconstruction)
    recon = cents[gid].copy()
    if rot is None:
        for s in range(S):
            recon[:, s * dsub:(s + 1) * dsub] += \
                codebooks[s][codes[:, s]]
    else:
        recon_rot = np.zeros((R, d), np.float32)
        for s in range(S):
            recon_rot[:, s * dsub:(s + 1) * dsub] = \
                codebooks[s][codes[:, s]]
        recon += recon_rot @ rot.T
    err = (slab - recon) * valid[:, None].astype(np.float32)
    # magnitude scales for the additive float-arithmetic headroom
    mag_sub = (np.sqrt(np.sum(slab.reshape(R, S, dsub) ** 2, axis=2))
               + np.sqrt(np.sum(recon.reshape(R, S, dsub) ** 2,
                                axis=2))) * valid[:, None]
    mag_row = (np.sqrt(np.sum(slab ** 2, axis=1))
               + np.sqrt(np.sum(recon ** 2, axis=1))) * valid
    e_sub = np.sqrt(np.maximum(
        np.sum(err.reshape(R, S, dsub) ** 2, axis=2), 0.0))
    eq_sub = ((e_sub.max(axis=0) if R else np.zeros(S))
              * _PQ_EQ_HEADROOM
              + _PQ_EQ_ABS * (mag_sub.max(axis=0) if R
                              else np.zeros(S)))
    eq_rows = (np.sqrt(np.maximum(np.sum(err ** 2, axis=1), 0.0))
               * _PQ_EQ_HEADROOM + _PQ_EQ_ABS * mag_row)
    # per-list certificate sidecars: the max row error bound and the
    # max reconstructed-RESIDUAL norm (the ADC kernel's hi/lo split
    # error scales with ‖x‖·‖r̂‖, so the envelope stays tight even for
    # data living far from the origin)
    rhat = recon - cents[gid]
    rhat_norm = np.sqrt(np.maximum(np.sum(rhat * rhat, axis=1), 0.0)) \
        * valid.astype(np.float32)
    eq_list = np.zeros(L, np.float32)
    rhat_list = np.zeros(L, np.float32)
    # per-list quantile sketch of the row error bounds (q50/q90/max
    # over the VALID rows) — the chooser's expected-rerun model and
    # the explain plane read it; the certificate itself rides the
    # exact per-row sidecar
    eq_qlist = np.zeros((L, 3), np.float32)
    offs = np.asarray(flat.offsets)
    for l in range(L):
        w = int(padded[l])
        if w:
            o = int(offs[l])
            eq_list[l] = eq_rows[o:o + w].max()
            rhat_list[l] = rhat_norm[o:o + w].max()
            seg = eq_rows[o:o + w][valid[o:o + w]]
            if seg.size:
                eq_qlist[l] = np.quantile(seg, (0.5, 0.9, 1.0))
    resid_norm = np.sqrt(np.maximum(np.sum(resid * resid, axis=1),
                                    0.0))
    resid_med = float(np.median(resid_norm[valid])) if n_valid else 0.0
    yy_pq = np.where(valid, np.sum(recon * recon, axis=1), 0.0)

    idx = IvfPqIndex(
        centroids=flat.centroids, slab=flat.slab, ids=flat.ids,
        yy_slab=flat.yy_slab, offsets=flat.offsets, sizes=flat.sizes,
        padded_sizes=flat.padded_sizes, n_rows=m, d_orig=d,
        row_quantum=flat.row_quantum,
        n_probes_default=flat.n_probes_default, Qb=flat.Qb,
        kmeans_iters=flat.kmeans_iters, balanced=balanced,
        pq_dim=S, pq_bits=pq_bits,
        codebooks=jnp.asarray(codebooks),
        codes=jnp.asarray(pack_pq_codes(codes, pq_bits)),
        yy_pq=jnp.asarray(yy_pq.astype(np.float32).reshape(R, 1)),
        pq_eq_rows=jnp.asarray(eq_rows.astype(np.float32)),
        pq_eq_sub=np.asarray(eq_sub, np.float32),
        pq_eq_list=jnp.asarray(eq_list),
        pq_rhat_list=jnp.asarray(rhat_list),
        pq_mode=pq_mode,
        pq_rot=None if rot is None else jnp.asarray(rot),
        pq_eq_qlist=np.asarray(eq_qlist, np.float32),
        pq_resid_med=resid_med)
    emit_marker("pq_build", n_rows=m, n_lists=L, pq_dim=S,
                pq_bits=pq_bits, pq_mode=pq_mode,
                code_bytes_per_row=idx.code_bytes,
                eq_row_max=round(float(eq_rows.max()) if R else 0.0, 6),
                eq_sub_max=round(float(eq_sub.max()), 6),
                resid_med=round(resid_med, 6),
                compression=round(4.0 * d / (idx.code_bytes + 8), 2))
    return idx


# ------------------------------------------------------------- search
def _pq_certify(bound, theta, widen):
    """certified ⇔ no probed row outside the candidate pool can beat
    the exact k-th value. ``bound`` is the kernel's pooled rest-min of
    the PER-ROW certified lower bounds ``(max(√d2_adc − Eq_row, 0))²``
    (the adaptive certificate — each row is widened by ITS OWN
    recorded error, not the probed lists' worst case), so ``widen``
    carries only the kernel-precision envelope. Module-level so the
    certificate-failure tests can force the widen/rerun rungs."""
    return bound >= theta + widen


def _pq_pool_finish(x, xx, rows, slab, ids, yy_slab, starts_qm, psizes,
                    k: int, P: int, W: int):
    """Exact-rescore the pooled candidate rows from the f32 slab with
    the query-major scorer's own formula, reorder into query-major
    candidate order (probe slot × window column — ties break exactly
    like :func:`~raft_tpu.ann.ivf_flat._fine_scan`) and select top-k.
    Unlike the flat `_pool_finish`, rows whose id is MASKED (−1 —
    tombstones on the mutable plane) score +inf: the codes slab keeps
    serving after a delete without a repack."""
    valid = rows >= 0
    rc = jnp.maximum(rows, 0)
    cid = jnp.where(valid, jnp.take(ids, rc), -1)
    valid = valid & (cid >= 0)
    yc = jnp.take(slab, rc, axis=0)                    # [nq, C2, d]
    d2 = (xx + jnp.take(yy_slab, rc)
          - 2.0 * jnp.einsum("qd,qcd->qc", x, yc,
                             precision=jax.lax.Precision.HIGHEST))
    d2 = jnp.where(valid, jnp.maximum(d2, 0.0), jnp.inf)
    w = rows[:, :, None] - starts_qm[:, None, :]       # [nq, C2, P]
    match = ((w >= 0) & (w < psizes[:, None, :])
             & valid[:, :, None])
    slot = jnp.argmax(match, axis=2).astype(jnp.int32)
    col = jnp.take_along_axis(w, slot[:, :, None], axis=2)[:, :, 0]
    key = jnp.where(jnp.any(match, axis=2),
                    slot * W + col.astype(jnp.int32), P * W)
    order = jnp.argsort(key, axis=1)
    d2s = jnp.take_along_axis(d2, order, axis=1)
    cids = jnp.take_along_axis(cid, order, axis=1)
    neg, pos = jax.lax.top_k(-d2s, k)
    vals = -neg
    out_ids = jnp.take_along_axis(cids, pos, axis=1)
    return vals, jnp.where(jnp.isfinite(vals), out_ids, -1)


def _pq_lut(x, codebooks, S: int, dsub: int):
    """The per-query ADC table: ``lut[q, s·K + j] = x_{q,s} ·
    cb_s[j]`` — f32 HIGHEST, flattened subspace-major for the kernel's
    one-hot contraction."""
    nq = x.shape[0]
    xr = x.reshape(nq, S, dsub)
    lut = jnp.einsum("qsd,skd->qsk", xr, codebooks,
                     precision=jax.lax.Precision.HIGHEST)
    return lut.reshape(nq, -1)


def pq_scan_chunk(index: IvfPqIndex, xs, probes_np, pr, st, ps,
                  k: int, P: int, W: int, ids=None,
                  pool_depth: int = 2):
    """One list-major ADC chunk → (vals, ids, certified, margin).
    ``ids`` overrides the slab id map (the mutable plane passes its
    tombstone-masked ``ids_live``); the certificate compares against
    the same masked oracle, so a failure's rerun returns identical id
    sets. ``pool_depth`` ∈ (2, 4, 8) sizes the per-lane-class
    candidate pool (the ``pq_widen`` rung re-runs at 4/8). ``margin``
    (bound − θ − e_k, pre-rerun) feeds the explain plane.

    The certificate is PER-QUERY ADAPTIVE: the kernel pools each
    streamed row's certified true-distance lower bound
    ``(max(√d2_adc − Eq_row, 0))²`` (its own recorded round-trip
    error, streamed as a sidecar), so the pooled rest-min needs only
    the kernel-precision envelope ``e_k`` on top of ``θ`` — the
    per-list worst-case ``2√θ·Eq + Eq²`` widening the pre-adaptive
    certificate paid survives only as the explain plane's
    ``pq_margin_adaptive_gain`` delta."""
    from raft_tpu.ops.fine_scan_pallas import pad_window
    from raft_tpu.ops.pq_scan_pallas import pq_scan_list_major

    if ids is None:
        ids = index.ids
    nq, d = xs.shape
    S, dsub = index.pq_dim, index.dsub
    Wk = pad_window(W)
    sched = build_list_schedule(index, probes_np)
    xx = jnp.sum(xs * xs, axis=1, keepdims=True)
    xp, pp, nqp = _pad_kernel_operands(xs, pr)
    xxp = jnp.concatenate(
        [xx, jnp.zeros((nqp - nq, 1), jnp.float32)]) if nqp > nq else xx
    # the learned rotation applies to the QUERY side of the ADC table
    # only: codes encode r·R, and x·(r̂'Rᵀ) = (x·R)·r̂' — the centroid
    # cross term and the exact rescore stay in the original basis
    xq = xp if index.pq_rot is None else jnp.matmul(
        xp, index.pq_rot, precision=jax.lax.Precision.HIGHEST)
    lut = _pq_lut(xq, index.codebooks, S, dsub)
    lids = jnp.maximum(jnp.asarray(sched.sched[3]), 0)
    cents = jnp.take(index.centroids, lids, axis=0)     # [Lp, d]
    cdot = jnp.einsum("qd,ld->ql", xp, cents,
                      precision=jax.lax.Precision.HIGHEST)
    pool = pq_scan_list_major(
        jnp.asarray(sched.sched), xxp, pp, cdot, lut, index.codes,
        index.yy_pq, index.pq_eq_col, Wk=Wk, pq_bits=index.pq_bits,
        pool_depth=pool_depth)
    rows = jnp.concatenate(
        [pool[2 * t + 1][:nq] for t in range(pool_depth)], axis=1)
    vals, out_ids = _pq_pool_finish(xs, xx, rows, index.slab, ids,
                                    index.yy_slab, st, ps, k, P, W)
    # adaptive completeness certificate: every probed row OUTSIDE the
    # pool has certified lower bound ≥ the pooled rest-min, so only
    # the ADC kernel's numeric term over the score magnitudes widens θ
    theta = vals[:, k - 1]
    bound = jnp.min(pool[2 * pool_depth][:nq], axis=1)
    host = _list_host(index)
    eq_w = jnp.max(jnp.take(index.pq_eq_list, pr), axis=1)
    yymax = jnp.max(jnp.take(host["yy_lmax"], pr), axis=1)
    rhat_w = jnp.max(jnp.take(index.pq_rhat_list, pr), axis=1)
    # kernel-precision envelope: the ADC table's bf16 hi/lo two-pass
    # split carries ≤ ~2⁻¹⁷ relative error per entry against a
    # magnitude bounded by ‖x‖·‖r̂‖ (Cauchy-Schwarz over the subspace
    # concatenation — the RESIDUAL norm, not the row norm, which is
    # what keeps this tight for data far from the origin), plus the
    # f32 adds/accumulation over the full score magnitude. The
    # lower-bound map z ↦ (max(√z − Eq, 0))² is 1-Lipschitz, so the
    # same envelope bounds the pooled certificate scores.
    xnorm = jnp.sqrt(xx[:, 0])
    span = (xnorm + jnp.sqrt(yymax) + eq_w) ** 2
    e_k = (2.0 ** -15 * xnorm * rhat_w
           + (2.0 ** -20 + d * 2.0 ** -24) * span)
    certified = _pq_certify(bound, theta, e_k)
    if explain.active() is not None:
        # what the pre-adaptive per-list worst-case certificate would
        # have ADDED to the widening — the adaptive margin gain
        sq_t = jnp.sqrt(jnp.maximum(theta, 0.0))
        gain = 2.0 * sq_t * eq_w + eq_w * eq_w
        explain.note(pq_margin_adaptive_gain=round(
            float(jnp.mean(gain)), 6))
    return vals, out_ids, certified, bound - (theta + e_k)


def expected_pq_rerun_frac(index: IvfPqIndex, probes_np=None
                           ) -> Tuple[float, str]:
    """Measured-or-modeled expected certificate-rerun fraction for
    ``index`` — the number the chooser folds into the ADC-vs-flat
    byte comparison (the PR-15 blind spot: best-case codes bytes hid
    the exact-rerun cost on hard data).

    MEASURED wins when the quality plane has seen enough checks at the
    ``ann.search_ivf_pq`` site this process. Otherwise the MODEL reads
    the build-time per-list quantile sketch (``pq_eq_qlist``,
    restricted to the probed lists when given): when a typical row's
    recorded quantization error approaches the median residual norm,
    ADC ordering is noise at the margin scale and the certificate
    reruns — the prior is ``min(1, (q90_Eq / median‖y − c‖)²)``.
    Returns ``(frac, source)`` with source ∈ ("measured", "modeled",
    "unmodeled")."""
    from raft_tpu.observability.quality import measured_rerun_frac

    m = measured_rerun_frac("ann.search_ivf_pq")
    if m is not None:
        return float(m), "measured"
    q = getattr(index, "pq_eq_qlist", None)
    med = float(getattr(index, "pq_resid_med", 0.0) or 0.0)
    if q is None or med <= 0.0:
        return 0.0, "unmodeled"
    q = np.asarray(q)
    if probes_np is not None and q.ndim == 2 and q.shape[0]:
        lists = np.unique(np.asarray(probes_np).ravel())
        lists = lists[(lists >= 0) & (lists < q.shape[0])]
        if lists.size:
            q = q[lists]
    live = q[q[:, 2] > 0.0] if q.size else q
    if not live.size:
        return 0.0, "unmodeled"
    q90 = float(np.median(live[:, 1]))
    ratio = q90 / med
    return float(min(1.0, ratio * ratio)), "modeled"


def resolve_pq_scan(index: IvfPqIndex, nq: int, k: int, P: int, W: int,
                    requested: Optional[str] = None,
                    probes_np=None, chunk: Optional[int] = None) -> str:
    """EFFECTIVE schedule for one :func:`search_ivf_pq` call — the
    ``resolve_fine_scan``-style chooser. ``None`` reads
    ``RAFT_TPU_IVF_PQ_SCAN`` (default ``auto``).

    Envelope (outside it every request runs the flat scan, with a
    logged downgrade for an explicit ``pq``): the slab must cover one
    kernel window, ``k`` the 256-slot candidate pool, the probe count
    the 128-lane probe table, the ADC cell the scoped-VMEM budget, and
    on real TPUs the flattened table width ``pq_dim · 2^pq_bits`` must
    be lane-aligned.

    ``auto`` consults the schema-7 ``pq`` tune-table column
    (:func:`raft_tpu.tune.ivf.pq_scan_config`, mode-aware) first,
    then the cost-model crossover (:func:`~raft_tpu.observability.
    costmodel.choose_pq_scan` over the pq-aware traffic model on the
    index's actual list-size histogram) — priced at the EXPECTED
    bytes including the measured-or-modeled certificate-rerun
    fraction (:func:`expected_pq_rerun_frac`), with a logged
    downgrade when the rerun pricing flips the best-case pick."""
    from raft_tpu.observability.costmodel import (choose_pq_scan,
                                                  ivf_traffic_model)
    from raft_tpu.ops.fine_scan_pallas import pad_window
    from raft_tpu.ops.fused_l2_topk_pallas import vmem_budget
    from raft_tpu.ops.pq_scan_pallas import pq_scan_vmem_footprint
    from raft_tpu.ops.utils import interpret_mode

    req = requested if requested is not None \
        else env.get("RAFT_TPU_IVF_PQ_SCAN")
    if req not in PQ_SCANS:
        raise ValueError(f"pq_scan must be one of {PQ_SCANS}, "
                         f"got {req!r}")
    if req == "flat":
        return "flat"
    Wk = pad_window(W)
    S, K = index.pq_dim, index.pq_k
    nqp = -(-min(nq, chunk or nq) // 8) * 8
    from raft_tpu.ann.ivf_flat import _list_cells
    from raft_tpu.ops.fine_scan_pallas import LISTS_PER_CELL

    Lp = _list_cells(min(nq, chunk or nq) * P, index.n_lists) \
        * LISTS_PER_CELL
    reason = None
    if index.slab_rows < Wk:
        reason = f"slab rows {index.slab_rows} < kernel window {Wk}"
    elif k > _LIST_K_MAX:
        reason = f"k={k} > {_LIST_K_MAX} exceeds the candidate pool"
    elif P > 128:
        reason = f"n_probes={P} > 128 exceeds the probe table"
    elif pq_scan_vmem_footprint(Wk, nqp, S, K, Lp,
                                index.pq_bits) > vmem_budget():
        reason = "ADC cell footprint over the scoped-VMEM budget"
    elif not interpret_mode() and (S * K) % 128:
        reason = (f"ADC table width {S}x{K} is not lane-aligned on a "
                  f"real TPU")
    if reason is not None:
        if req == "pq":
            from raft_tpu.core.logger import log_warn

            log_warn("pq_scan='pq' outside the ADC envelope (%s) — "
                     "using the flat scan for this call", reason)
        return "flat"
    if req == "pq":
        return "pq"
    # auto — tuned table first, then the cost-model crossover at the
    # rerun-aware expected bytes
    from raft_tpu.tune.ivf import pq_scan_config

    tuned = pq_scan_config(index.n_lists, P, index.pq_bits,
                           pq_mode=getattr(index, "pq_mode", "plain"))
    if tuned in ("pq", "flat"):
        return tuned
    frac, src = expected_pq_rerun_frac(index, probes_np)
    model = ivf_traffic_model(
        nq, index.n_rows, index.d_orig, k, index.n_lists, P, W,
        index.slab_rows, list_sizes=index._np_sizes,
        padded_sizes=index._np_padded, pq_dim=S,
        pq_bits=index.pq_bits, pq_rerun_frac=frac)
    pick = choose_pq_scan(model)
    if pick == "flat" and choose_pq_scan(model, rerun_frac=0.0) == "pq":
        from raft_tpu.core.logger import log_warn

        log_warn("pq_scan auto: expected certificate-rerun fraction "
                 "%.2f (%s) prices the ADC scan above the flat scan "
                 "— downgrading to flat for this call", frac, src)
        emit_marker("pq_chooser_downgrade",
                    rerun_frac=round(frac, 4), source=src)
        explain.note(pq_chooser_downgrade={
            "rerun_frac": round(frac, 4), "source": src})
    return pick


@instrument("ann.search_ivf_pq")
def search_ivf_pq(res, index: IvfPqIndex, queries, k: int,
                  n_probes: Optional[int] = None,
                  pq_scan: Optional[str] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Approximate top-k against an :class:`IvfPqIndex`.

    (ref: ivf_pq::search + its refine step — ADC over the compressed
    lists, then exact re-ranking of the shortlist.) Returns (d2
    [nq, k] ascending, global ids [nq, k]) like ``search_ivf_flat``;
    the returned values are EXACT f32 distances (every candidate is
    rescored from the retained f32 slab — the mandatory refine), and
    the id set is certified identical to the flat scan's over the same
    probe lists: a failed completeness certificate reruns the exact
    f32 scan for that chunk, and a failed kernel dispatch (fault site
    ``pq_scan``) degrades to the f32/int8 query-major scan with a
    recorded degradation.

    ``pq_scan`` ∈ :data:`PQ_SCANS` picks the schedule (``None`` reads
    ``RAFT_TPU_IVF_PQ_SCAN``); ``n_probes ≥ n_lists`` (or ``k`` past
    the probed capacity) degrades to certified-EXACT search exactly
    like IVF-Flat."""
    fault_point("ivf_search")
    res = ensure_resources(res)
    expects(isinstance(index, IvfPqIndex),
            "search_ivf_pq: index must be an IvfPqIndex (got %s)",
            type(index).__name__)
    x = jnp.asarray(queries, jnp.float32)
    expects(x.ndim == 2 and x.shape[1] == index.d_orig,
            "search_ivf_pq: query width %s != index %d",
            x.shape[1:], index.d_orig)
    expects(k >= 1, "search_ivf_pq: k must be >= 1")
    expects(k <= index.n_rows,
            "search_ivf_pq: k=%d > index size %d", k, index.n_rows)
    nq = x.shape[0]
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    L = index.n_lists
    if n_probes is None:
        from raft_tpu.ann.ivf_flat import _env_int

        P = _env_int("RAFT_TPU_ANN_NPROBES", index.n_probes_default)
    else:
        P = int(n_probes)
    expects(P >= 1, "search_ivf_pq: n_probes must be >= 1, got %d", P)
    W = index.probe_window
    reason = None
    if P >= L:
        reason = f"n_probes={P} >= n_lists={L}"
    elif k > P * W:
        reason = (f"k={k} exceeds the probed candidate capacity "
                  f"{P}x{W}={P * W}")
    if reason is not None:
        from raft_tpu.core.logger import log_warn

        log_warn("search_ivf_pq: %s — degrading to exact search over "
                 "the f32 slab for this call", reason)
        emit_marker("ivf_exact_degrade", reason=reason, k=k,
                    n_probes=P, n_lists=L)
        explain.note(plane="ivf_pq", exact_degrade=reason,
                     n_probes=P, n_lists=L, k=k)
        return _exact_search(res, index, x, k)

    probes = _coarse_probe(res, index.centroids, x, P)       # [nq, P]
    probes_host = np.asarray(probes)
    starts = jnp.take(index.offsets[:-1], probes)
    psizes = jnp.take(index.padded_sizes, probes)
    d = x.shape[1]
    chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
    schedule = resolve_pq_scan(index, nq, k, P, W, pq_scan,
                               probes_np=probes_host, chunk=chunk)
    emit_marker("ivf_pq_search", nq=nq, k=k, n_probes=P, n_lists=L,
                pq_dim=index.pq_dim, pq_bits=index.pq_bits,
                schedule=schedule)
    if explain.active() is not None:
        sz = np.asarray(index.sizes)[probes_host]
        explain.note(plane="ivf_pq", n_probes=P, n_lists=L, k=k,
                     pq_bits=index.pq_bits, pq_dim=index.pq_dim,
                     pq_scan=schedule,
                     probed_lists=probes_host[0].tolist(),
                     probed_rows=int(sz.sum()),
                     probed_size_hist={
                         "min": int(sz.min()), "p50": float(
                             np.percentile(sz, 50)),
                         "max": int(sz.max())},
                     pool_width=256)
    if schedule == "pq":
        try:
            fault_point("pq_scan")
            return _search_pq(res, index, x, probes, probes_host,
                              starts, psizes, k, P, W, chunk)
        except DeadlineExceededError:
            raise               # the caller's global budget — never eaten
        except Exception as e:
            from raft_tpu.core.logger import log_warn

            record_degradation("pq_scan", "flat")
            emit_marker("pq_scan_degrade",
                        reason=f"{type(e).__name__}: {e}"[:160])
            explain.note(pq_scan_degrade=f"{type(e).__name__}"[:64])
            log_warn("PQ ADC scan failed (%s: %s) — degrading to the "
                     "flat fine scan for this call",
                     type(e).__name__, e)
    # the flat rung: the uncompressed f32 (or int8) fine scan — the
    # degradation target and the chooser's "flat" pick share one path
    if nq <= chunk:
        return _query_major_chunk(index, x, starts, psizes, k, P, W)
    outs = [_query_major_chunk(index, x[s:s + chunk],
                               starts[s:s + chunk],
                               psizes[s:s + chunk], k, P, W)
            for s in range(0, nq, chunk)]
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


def _search_pq(res, index: IvfPqIndex, x, probes, probes_host, starts,
               psizes, k: int, P: int, W: int, chunk: int):
    """The ADC driver: per chunk, run :func:`pq_scan_chunk`, walk any
    certificate-failing rows down the widen rungs (2x / 4x candidate
    pool, re-ADC, re-certify), and rerun whatever still fails through
    the exact f32 scan — returned id sets match the flat scan's over
    the same probes in EVERY case."""
    from raft_tpu.ann.ivf_flat import _list_cells
    from raft_tpu.ops.fine_scan_pallas import (LISTS_PER_CELL,
                                               pad_window)
    from raft_tpu.ops.fused_l2_topk_pallas import vmem_budget
    from raft_tpu.ops.pq_scan_pallas import pq_scan_vmem_footprint

    nq = x.shape[0]
    widen_cap = int(env.get("RAFT_TPU_ANN_PQ_WIDEN"))
    try:
        res.profiler.capture_fn(
            "ann.pq_scan", _pq_lut, x[:min(nq, chunk)],
            index.codebooks, index.pq_dim, index.dsub)
    except Exception:
        pass

    def run_chunk(s0: int, s1: int):
        xs, pr = x[s0:s1], probes[s0:s1]
        st, ps = starts[s0:s1], psizes[s0:s1]
        nq_c = int(xs.shape[0])
        vals, ids_c, ok, margin = pq_scan_chunk(
            index, xs, probes_host[s0:s1], pr, st, ps, k, P, W)
        explain.note_margin("ann.search_ivf_pq", margin)
        n_fail0 = n_fail = int(jnp.sum(~ok))
        depth_used = 2
        if n_fail:
            # the widen rung: before escalating to the exact scan,
            # re-run the ADC with a deeper candidate pool (256 -> 512
            # -> 1024 slots) and re-certify — on margin-starved rows
            # the pooled rest-min usually clears theta + e_k once the
            # pool holds the near-boundary candidates
            Wk = pad_window(W)
            nqp = -(-nq_c // 8) * 8
            Lp = _list_cells(nq_c * P, index.n_lists) * LISTS_PER_CELL
            for factor in (2, 4):
                if factor > widen_cap or not n_fail:
                    break
                depth = 2 * factor
                if pq_scan_vmem_footprint(
                        Wk, nqp, index.pq_dim, index.pq_k, Lp,
                        index.pq_bits,
                        pool_depth=depth) > vmem_budget():
                    break
                try:
                    fault_point("pq_widen")
                    wv, wi, wok, _wm = pq_scan_chunk(
                        index, xs, probes_host[s0:s1], pr, st, ps,
                        k, P, W, pool_depth=depth)
                except DeadlineExceededError:
                    raise       # the global budget — never eaten
                except Exception as e:
                    from raft_tpu.core.logger import log_warn

                    record_degradation("pq_widen", "exact")
                    emit_marker("pq_widen_degrade",
                                reason=f"{type(e).__name__}: "
                                       f"{e}"[:160])
                    log_warn("PQ widen rung x%d failed (%s: %s) — "
                             "escalating straight to the exact "
                             "rerun", factor, type(e).__name__, e)
                    break
                okc = ok[:, None]
                vals = jnp.where(okc, vals, wv)
                ids_c = jnp.where(okc, ids_c, wi)
                ok = ok | wok
                depth_used = depth
                n_fail = int(jnp.sum(~ok))
        # same host sync the certified gather paths already pay — the
        # PQ slice of the certificate/fixup evidence plane
        record_certificate("ann.search_ivf_pq",
                           n_queries=nq_c, n_fail=n_fail,
                           pool_width=128 * depth_used,
                           fixup_rows=n_fail or None,
                           rerun=bool(n_fail), pq_bits=index.pq_bits,
                           n_probes=P)
        record_pq_rungs("ann.search_ivf_pq",
                        certified=nq_c - n_fail0,
                        widened=n_fail0 - n_fail, exact_rerun=n_fail)
        if explain.active() is not None:
            explain.note(pq_rungs={
                "certified": nq_c - n_fail0,
                "widened": n_fail0 - n_fail, "exact_rerun": n_fail})
        if n_fail:
            # the true top-k (or a tie) may hide outside the pooled
            # candidates: rerun the chunk through the exact f32 scan
            # and keep certified rows — bytes saved stand, correctness
            # never rides on the margin
            emit_marker("pq_cert_fallback", n_fail=n_fail, nq=nq_c)
            explain.note(rerun="pq_exact", rerun_rows=n_fail)
            fv, fi = _fine_scan(xs, index.slab, index.ids,
                                index.yy_slab, st, ps, k=k, P=P, W=W)
            okc = ok[:, None]
            vals = jnp.where(okc, vals, fv)
            ids_c = jnp.where(okc, ids_c, fi)
        return vals, ids_c

    if nq <= chunk:
        return run_chunk(0, nq)
    outs = [run_chunk(s, min(s + chunk, nq))
            for s in range(0, nq, chunk)]
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


def warm_pq_scan(res, index: IvfPqIndex, nq: int, k: int,
                 n_probes: int) -> int:
    """Pre-compile every program a serving bucket of ``nq`` queries
    can reach on the PQ plane: the flat fallback/degradation programs
    (through the public entry, so the chunking and rerun programs warm
    too) and one ADC program per (power-of-two schedule-cell rung x
    certification pool depth — the widen ladder up to
    ``RAFT_TPU_ANN_PQ_WIDEN``) — mirrors
    :func:`~raft_tpu.ann.ivf_flat.warm_fine_scan` so a live request
    never pays a compile whichever way the chooser (or the
    certificate) lands. Returns the warmed ADC program count (0 =
    outside the ADC envelope)."""
    from raft_tpu.ops.fine_scan_pallas import (LISTS_PER_CELL,
                                               pad_window)
    from raft_tpu.ops.pq_scan_pallas import pq_scan_list_major

    P = min(max(1, int(n_probes)), index.n_lists)
    if P >= index.n_lists or nq < 1:
        return 0            # the degenerate-exact plane — one schedule
    W = index.probe_window
    Wk = pad_window(W)
    d = index.d_orig
    x0 = np.zeros((nq, d), np.float32)
    out = search_ivf_pq(res, index, x0, k, n_probes=P, pq_scan="flat")
    jax.block_until_ready(out)
    if resolve_pq_scan(index, nq, k, P, W, "pq") != "pq":
        return 0
    chunk = max(8, _FINE_TILE // max(1, P * W * max(d, 1)))
    sizes = sorted({min(nq, chunk), nq % chunk or min(nq, chunk)})
    cap = max(1, -(-index.n_lists // LISTS_PER_CELL))
    rungs = sorted({min(1 << b, cap)
                    for b in range(cap.bit_length() + 1)})
    widen_cap = int(env.get("RAFT_TPU_ANN_PQ_WIDEN"))
    depths = [2] + [2 * f for f in (2, 4) if f <= widen_cap]
    S, K = index.pq_dim, index.pq_k
    warmed = 0
    for nq_c in sizes:
        nqp = -(-nq_c // 8) * 8
        xx0 = jnp.zeros((nqp, 1), jnp.float32)
        pp0 = jnp.full((nqp, 128), -2, jnp.int32)
        lut0 = jnp.zeros((nqp, S * K), jnp.float32)
        for cells in rungs:
            Lp = cells * LISTS_PER_CELL
            sched = np.zeros((4, Lp), np.int32)
            sched[3, :] = -1
            for depth in depths:
                out = pq_scan_list_major(
                    jnp.asarray(sched), xx0, pp0,
                    jnp.zeros((nqp, Lp), jnp.float32), lut0,
                    index.codes, index.yy_pq, index.pq_eq_col,
                    Wk=Wk, pq_bits=index.pq_bits, pool_depth=depth)
                jax.block_until_ready(out)
                warmed += 1
    return warmed
