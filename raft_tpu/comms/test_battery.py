"""Built-in communicator self-tests.

(ref: cpp/include/raft/comms/comms_test.hpp public wrappers over
comms/detail/test.hpp (534 LoC): test_collective_allreduce:31,
…broadcast:62, …reduce:97, …allgather:133, …gather:170, …gatherv:207,
…reducescatter:266, test_pointToPoint_simple_send_recv:301,
…device_send_or_recv:366, …device_sendrecv:408,
…device_multicast_sendrecv:454, test_commsplit:513 — each driven from
python in raft-dask (comms_utils.pyx:68-243 ``perform_test_comms_*``).

Here each test builds rank-identified data, runs the collective over the
mesh, and checks the SPMD-identity the reference checks. They run on any
mesh — the 8-device virtual CPU mesh in CI, a real pod on TPU.)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.comms.comms import Op
from raft_tpu.comms.host_comms import HostComms


def _ranks(comms: HostComms):
    return np.arange(comms.size)


def _fetch(x) -> np.ndarray:
    """Materialize a (possibly multi-process-sharded) result on every
    host — the multihost analog of the reference tests' cudaMemcpy-back.
    (np.asarray alone cannot read non-addressable shards.)"""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def perform_test_comm_allreduce(comms: HostComms) -> bool:
    """(ref: detail/test.hpp:31 — each rank contributes 1; expect size.)"""
    x = jnp.ones((comms.size, 1), jnp.float32)
    out = _fetch(comms.allreduce(x, Op.SUM))
    return bool((out == comms.size).all())


def perform_test_comm_bcast(comms: HostComms, root: int = 0) -> bool:
    """(ref: detail/test.hpp:62 — root's value lands everywhere.)"""
    x = jnp.asarray(_ranks(comms)[:, None] + 100.0, jnp.float32)
    out = _fetch(comms.bcast(x, root=root))
    return bool((out == 100.0 + root).all())


def perform_test_comm_reduce(comms: HostComms, root: int = 0) -> bool:
    """(ref: detail/test.hpp:97 — the reference asserts only the root;
    non-root buffers stay untouched, here = the rank's own input.)"""
    x = jnp.asarray(_ranks(comms)[:, None], jnp.float32)
    out = _fetch(comms.reduce(x, root=root, op=Op.SUM))
    want = _ranks(comms).sum()
    ok_root = out[root, 0] == want
    others = np.delete(out[:, 0], root)
    untouched = np.delete(_ranks(comms), root)
    return bool(ok_root and (others == untouched).all())


def perform_test_comm_allgather(comms: HostComms) -> bool:
    """(ref: detail/test.hpp:133 — every rank sees every rank's value.)"""
    x = jnp.asarray(_ranks(comms)[:, None], jnp.float32)
    out = _fetch(comms.allgather(x))  # [size, size, 1]
    return bool(all((out[r, :, 0] == _ranks(comms)).all()
                    for r in range(comms.size)))


def perform_test_comm_gather(comms: HostComms, root: int = 0) -> bool:
    """(ref: detail/test.hpp:170)"""
    x = jnp.asarray(_ranks(comms)[:, None], jnp.float32)
    out = _fetch(comms.gather(x, root=root))
    return bool((out[root, :, 0] == _ranks(comms)).all())


def perform_test_comm_gatherv(comms: HostComms, root: int = 0) -> bool:
    """(ref: detail/test.hpp:207 — rank r contributes r+1 copies of r.)"""
    size = comms.size
    counts = tuple(r + 1 for r in range(size))
    maxlen = max(counts)
    x = np.zeros((size, maxlen), np.float32)
    for r in range(size):
        x[r, : counts[r]] = r
    out = _fetch(comms.gatherv(jnp.asarray(x), counts, root=root))
    expected = np.concatenate([np.full(c, r) for r, c in enumerate(counts)])
    return bool((out[root] == expected).all())


def perform_test_comm_reducescatter(comms: HostComms) -> bool:
    """(ref: detail/test.hpp:266 — each rank gets its slice of the sum.)"""
    size = comms.size
    x = jnp.ones((size, size), jnp.float32)
    out = _fetch(comms.reducescatter(x, Op.SUM))  # [size, 1]
    return bool((out == size).all())


def perform_test_comm_device_sendrecv(comms: HostComms) -> bool:
    """Ring shift by one. (ref: detail/test.hpp:408
    test_pointToPoint_device_sendrecv; also covers :301/:366 — host p2p and
    send-or-recv collapse into the same ppermute on an SPMD mesh.)"""
    x = jnp.asarray(_ranks(comms)[:, None], jnp.float32)
    out = _fetch(comms.device_sendrecv(x, shift=1))
    expected = np.roll(_ranks(comms), 1)  # rank r receives from r-1
    return bool((out[:, 0] == expected).all())


def perform_test_comm_device_multicast_sendrecv(comms: HostComms) -> bool:
    """(ref: detail/test.hpp:454)"""
    x = jnp.asarray(_ranks(comms)[:, None], jnp.float32)
    out = _fetch(comms.device_multicast_sendrecv(x))
    return bool(all((out[r, :, 0] == _ranks(comms)).all()
                    for r in range(comms.size)))


def perform_test_comm_send_recv(comms: HostComms, num_trials: int = 2) -> bool:
    """Host p2p all-to-all: every rank isends its id to every other rank
    (tag 0), irecvs from all, waitall, verifies. (ref: detail/test.hpp:301
    test_pointToPoint_simple_send_recv — the same pattern per trial.)"""
    size = comms.size
    for _ in range(num_trials):
        reqs = []
        for dst in range(size):
            for src in range(size):
                if src != dst:
                    reqs.append(comms.irecv((1,), np.int32, src, dst))
        for src in range(size):
            for dst in range(size):
                if src != dst:
                    reqs.append(comms.isend(
                        np.asarray([src], np.int32), src, dst))
        if comms.waitall(reqs).value != 0:
            return False
        for r in reqs:
            if r.kind == "recv" and r.value is not None:
                if int(r.value[0]) != r.key[1]:
                    return False
        comms.barrier()
    return True


def perform_test_comm_device_send_or_recv(comms: HostComms,
                                          num_trials: int = 2) -> bool:
    """Disjoint send-OR-receive pairs: even rank r sends its id to r+1,
    odd ranks only receive and verify rank−1 arrived.
    (ref: detail/test.hpp:366 test_pointToPoint_device_send_or_recv.)"""
    size = comms.size
    for _ in range(num_trials):
        reqs = []
        for r in range(size):
            if r % 2 == 0 and r + 1 < size:
                reqs.append(comms.isend(np.asarray([r], np.int32), r, r + 1))
            elif r % 2 == 1:
                reqs.append(comms.irecv((1,), np.int32, r - 1, r))
        if comms.waitall(reqs).value != 0:
            return False
        for q in reqs:
            if q.kind == "recv" and q.value is not None:
                if int(q.value[0]) != q.key[1]:
                    return False
    return True


def perform_test_comm_split(comms: HostComms, row_axis: str, col_axis: str) -> bool:
    """2-D grid: row/col sub-communicator reductions.
    (ref: detail/test.hpp:513 test_commsplit; SURVEY §2.12
    sub-communicators.) ``comms`` must be built on a 2-D mesh."""
    mesh = comms.mesh
    rows = mesh.shape[row_axis]
    cols = mesh.shape[col_axis]
    row_comms = HostComms(mesh, row_axis)
    col_comms = HostComms(mesh, col_axis)
    # allreduce along rows only: each column-group sums independently
    x = jnp.ones((rows, 1), jnp.float32)
    out_r = _fetch(row_comms.allreduce(x))
    x2 = jnp.ones((cols, 1), jnp.float32)
    out_c = _fetch(col_comms.allreduce(x2))
    return bool((out_r == rows).all() and (out_c == cols).all())


ALL_TESTS = [
    perform_test_comm_allreduce,
    perform_test_comm_bcast,
    perform_test_comm_reduce,
    perform_test_comm_allgather,
    perform_test_comm_gather,
    perform_test_comm_gatherv,
    perform_test_comm_reducescatter,
    perform_test_comm_device_sendrecv,
    perform_test_comm_device_multicast_sendrecv,
    perform_test_comm_send_recv,
    perform_test_comm_device_send_or_recv,
]
