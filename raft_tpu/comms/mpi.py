"""MPI-launched multi-host bootstrap.

(ref: cpp/include/raft/comms/mpi_comms.hpp ``initialize_mpi_comms`` +
comms/detail/mpi_comms.hpp:99-121 — MPI provides rank/size/rendezvous and
NCCL is derived from the MPI communicator by broadcasting the uniqueId.
The TPU analog: when launched under mpirun/srun, read the launcher's
environment for (rank, size, coordinator) and hand them to
``jax.distributed.initialize`` — the coordinator plays the uniqueId
broadcast role; the resulting global device set forms the mesh.)
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from raft_tpu.core.error import expects


def detect_mpi_environment() -> Optional[Tuple[int, int]]:
    """(rank, size) from OpenMPI/MPICH/SLURM launcher env, or None."""
    for rank_var, size_var in (
        ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
        ("PMI_RANK", "PMI_SIZE"),
        ("SLURM_PROCID", "SLURM_NTASKS"),
    ):
        if rank_var in os.environ and size_var in os.environ:
            return int(os.environ[rank_var]), int(os.environ[size_var])
    return None


def initialize_mpi_comms(coordinator_address: Optional[str] = None,
                         coordinator_port: int = 8476):
    """Bootstrap jax.distributed from an MPI-style launch and return the
    initialized (rank, size). (ref: comms/mpi_comms.hpp
    ``initialize_mpi_comms``)"""
    import jax

    env = detect_mpi_environment()
    expects(env is not None,
            "initialize_mpi_comms: no MPI launcher environment detected")
    rank, size = env
    if coordinator_address is None:
        # every rank must agree on rank 0's address; the local HOSTNAME
        # would differ per host, so it must come from the launcher env
        host = os.environ.get("RAFT_TPU_COORDINATOR")
        expects(host is not None or size == 1,
                "initialize_mpi_comms: set RAFT_TPU_COORDINATOR to rank 0's "
                "host (or pass coordinator_address) for multi-host launches")
        coordinator_address = f"{host or 'localhost'}:{coordinator_port}"
    jax.distributed.initialize(coordinator_address, num_processes=size,
                               process_id=rank)
    return rank, size
