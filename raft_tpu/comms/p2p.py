"""Host point-to-point messaging — ``isend`` / ``irecv`` / ``waitall``.

(ref: cpp/include/raft/core/comms.hpp:130-140 — the ``comms_iface`` host
p2p rows (UCX-backed in std_comms); exercised by
comms/detail/test.hpp:301 ``test_pointToPoint_simple_send_recv``.)

TPU-native mapping: under the single-controller SPMD model a "rank" is a
mesh position, and its host-side owner is the process that holds the
rank's device (``device.process_index``). Host p2p is therefore
host-memory message passing between ``jax.distributed`` processes:

- ranks on the SAME process exchange through an in-memory mailbox;
- ranks on DIFFERENT processes exchange NumPy buffers over TCP sockets,
  with listener addresses rendezvoused once per process group through
  ``multihost_utils.process_allgather`` (the coordination-service
  analog of the reference's UCX address exchange).

Deliberate API deviation (documented in docs/using_comms.md): the
reference's per-rank ``isend(buf, size, dest, tag)`` has an implicit
source (the calling process IS the rank). Here one host drives all its
local ranks, so both ``src`` and ``dst`` are explicit. Calls for ranks
this process does not own are no-ops returning completed requests —
every process runs the same SPMD host program, so each transfer is
issued exactly once cluster-wide.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.comms.comms import Status

_HDR = struct.Struct("<iiiiq")     # comm fingerprint, src, dst, tag, nbytes
_DTYPE_HDR_LEN = 16                # fixed-width dtype string


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on a clean close BEFORE any byte, a
    raised error on mid-message truncation (a silently dropped message
    would surface only as the receiver's generic timeout)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ConnectionError(
                f"HostP2P: peer closed mid-message ({len(buf)}/{n} bytes)")
        buf += chunk
    return buf


def _my_ip() -> str:
    """The address peers can reach this process at: explicit override,
    else the kernel's outbound-route source address (a UDP connect sends
    no packets), else hostname resolution — which alone often yields
    127.0.0.1 on hosts whose /etc/hosts maps the hostname to loopback."""
    import os

    override = os.environ.get("RAFT_TPU_P2P_HOST")
    if override:
        return override
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return socket.gethostbyname(socket.gethostname())


class P2PRequest:
    """One pending transfer. ``result()`` is valid after ``waitall``
    (receives resolve to the received ndarray; sends to None)."""

    def __init__(self, kind: str, key: Tuple,
                 thread: Optional[threading.Thread] = None,
                 done: bool = False):
        self.kind = kind
        self.key = key
        self.thread = thread
        self.value: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.done = done

    def result(self) -> Optional[np.ndarray]:
        expects(self.done, "P2PRequest: waitall() has not completed this "
                           "request")
        return self.value


class HostP2P:
    """Mailbox + socket transport shared by all communicators of one
    process. One instance per (process, port-group); see
    :func:`get_transport`."""

    def __init__(self, n_processes: int, my_process: int):
        self.n_processes = n_processes
        self.my_process = my_process
        self._mail: Dict[Tuple, queue.Queue] = {}
        self._mail_lock = threading.Lock()
        self._fabric_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._peer_addrs: Optional[List[Tuple[str, int]]] = None
        self._listen_thread: Optional[threading.Thread] = None

    # -- mailbox -----------------------------------------------------------
    def _box(self, key: Tuple) -> queue.Queue:
        with self._mail_lock:
            if key not in self._mail:
                self._mail[key] = queue.Queue()
            return self._mail[key]

    def deliver_local(self, key, arr: np.ndarray) -> None:
        self._box(key).put(arr)

    # -- socket fabric (multi-process only) --------------------------------
    def _ensure_fabric(self) -> None:
        """Start the listener + rendezvous peer addresses. COLLECTIVE
        over processes (every process must reach first p2p use).
        Serialized by _fabric_lock — concurrent first uses would each
        run the allgather (duplicate collectives deadlock the group) —
        and _peer_addrs is published only once fully populated, so a
        thread racing past the fast-path None check can never index a
        half-built table."""
        if self._peer_addrs is not None or self.n_processes == 1:
            return
        with self._fabric_lock:
            if self._peer_addrs is not None:
                return
            from jax.experimental import multihost_utils

            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(("0.0.0.0", 0))
            listener.listen(self.n_processes * 4)
            port = listener.getsockname()[1]
            host = _my_ip()
            mine = np.frombuffer(
                (host + ":" + str(port)).ljust(64).encode(), np.uint8)
            allv = np.asarray(multihost_utils.process_allgather(mine))
            addrs = []
            for row in allv.reshape(self.n_processes, 64):
                h, p = bytes(row).decode().strip().rsplit(":", 1)
                addrs.append((h, int(p)))

            def serve():
                while True:
                    try:
                        conn, _ = listener.accept()
                    except OSError:
                        return
                    threading.Thread(target=self._recv_conn, args=(conn,),
                                     daemon=True).start()

            self._listener = listener
            self._listen_thread = threading.Thread(target=serve, daemon=True)
            self._listen_thread.start()
            self._peer_addrs = addrs

    def _recv_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                comm, src, dst, tag, nbytes = _HDR.unpack(hdr)
                dt = _recv_exact(conn, _DTYPE_HDR_LEN)
                shape_len = struct.unpack("<i", _recv_exact(conn, 4))[0]
                shape = struct.unpack(f"<{shape_len}q",
                                      _recv_exact(conn, 8 * shape_len))
                payload = _recv_exact(conn, nbytes) if nbytes else b""
                arr = np.frombuffer(
                    payload,
                    dtype=np.dtype(dt.decode().strip())).reshape(shape)
                self.deliver_local((comm, src, dst, tag), arr)
        except Exception:  # noqa: BLE001 — daemon thread: log, don't die
            from raft_tpu.core.logger import default_logger

            default_logger().error("HostP2P: dropped incoming message",
                                   exc_info=True)

    def send_remote(self, key, arr: np.ndarray, peer_process: int) -> None:
        self._ensure_fabric()
        comm, src, dst, tag = key
        host, port = self._peer_addrs[peer_process]
        with socket.create_connection((host, port), timeout=60) as s:
            data = np.ascontiguousarray(arr)
            s.sendall(_HDR.pack(comm, src, dst, tag, data.nbytes))
            s.sendall(str(data.dtype).ljust(_DTYPE_HDR_LEN).encode())
            s.sendall(struct.pack("<i", data.ndim))
            s.sendall(struct.pack(f"<{data.ndim}q", *data.shape))
            s.sendall(data.tobytes())

    def pop(self, key, timeout: float) -> np.ndarray:
        try:
            return self._box(key).get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"HostP2P: no message for (src, dst, tag)={key} within "
                f"{timeout}s — matching isend never issued?")


_transport: Optional[HostP2P] = None
_transport_lock = threading.Lock()


def get_transport() -> HostP2P:
    import jax

    global _transport
    with _transport_lock:
        if _transport is None:
            _transport = HostP2P(jax.process_count(), jax.process_index())
        return _transport


def comm_fingerprint(mesh_devices, axis_name: str) -> int:
    """A 31-bit namespace id for one communicator's rank line, mixed
    into every message key: without it, a parent communicator and a
    comm_split sub-communicator exchanging the same (src, dst, tag)
    through the shared process-global transport could cross-talk."""
    import zlib

    ids = ",".join(str(d.id) for d in mesh_devices)
    return zlib.crc32(f"{axis_name}|{ids}".encode()) & 0x7FFFFFFF


def isend(mesh_devices, x, src: int, dst: int, tag: int = 0,
          comm: int = 0) -> P2PRequest:
    """Post a host send of ``x`` from rank ``src`` to rank ``dst``.
    (ref: core/comms.hpp:130 ``isend``.) Immediate; complete via
    :func:`waitall`."""
    import jax

    expects(src != dst, "isend: src == dst == %d", src)
    t = get_transport()
    # rendezvous from the MAIN thread, BEFORE the ownership
    # early-returns and before any send thread exists: the fabric
    # allgather is collective over processes, so every process must
    # reach it at the same program point (a process that returned early
    # at "not ours to issue" while another blocked in the rendezvous
    # would interleave it with the next JAX collective — deadlock with
    # nothing but socket timeouts to surface it). Mirrors irecv.
    if t.n_processes > 1:
        t._ensure_fabric()
    key = (comm, src, dst, tag)
    src_proc = mesh_devices[src].process_index
    dst_proc = mesh_devices[dst].process_index
    if src_proc != jax.process_index():
        return P2PRequest("send", key, done=True)   # not ours to issue
    arr = np.asarray(x)
    if dst_proc == src_proc:
        t.deliver_local(key, arr)
        return P2PRequest("send", key, done=True)
    req = P2PRequest("send", key)

    def run():
        try:
            t.send_remote(key, arr, dst_proc)
        except Exception as e:  # noqa: BLE001 — re-raised by waitall
            req.error = e

    req.thread = threading.Thread(target=run, daemon=True)
    req.thread.start()
    return req


def irecv(mesh_devices, shape, dtype, src: int, dst: int,
          tag: int = 0, comm: int = 0) -> P2PRequest:
    """Post a host receive at rank ``dst`` from rank ``src``.
    (ref: core/comms.hpp:135 ``irecv``.) The (shape, dtype) are the
    caller's declared buffer — validated on completion."""
    import jax

    t = get_transport()
    if t.n_processes > 1:
        t._ensure_fabric()          # collective: all processes join
    key = (comm, src, dst, tag)
    if mesh_devices[dst].process_index != jax.process_index():
        return P2PRequest("recv", key, done=True)   # lands elsewhere
    req = P2PRequest("recv", key)
    req.shape, req.dtype = tuple(shape), np.dtype(dtype)
    return req


def waitall(requests: List[P2PRequest], timeout: float = 60.0) -> Status:
    """Complete all posted requests. (ref: core/comms.hpp:140
    ``waitall``.) Receives resolve their ``result()``; a failed send
    re-raises its transport error here rather than reporting SUCCESS
    for bytes that never left."""
    for r in requests:
        if r.done:
            continue
        if r.kind == "send":
            r.thread.join(timeout=timeout)
            expects(not r.thread.is_alive(),
                    "waitall: send %s timed out", r.key)
            if r.error is not None:
                raise r.error
            r.done = True
    for r in requests:
        if r.done:
            continue
        arr = get_transport().pop(r.key, timeout=timeout)
        expects(arr.shape == r.shape and arr.dtype == r.dtype,
                "waitall: received (%s, %s) for posted (%s, %s) on %s",
                arr.shape, arr.dtype, r.shape, r.dtype, r.key)
        r.value = arr
        r.done = True
    return Status.SUCCESS
