"""Host-level communicator: the handle-injected object.

(ref: cpp/include/raft/comms/std_comms.hpp:60 ``build_comms_nccl_only`` /
:108 ``build_comms_nccl_ucx`` building a ``comms_t`` that raft-dask injects
into each worker's handle via ``resource::set_comms``
(core/resource/comms.hpp). In the reference, every process owns one rank
and calls collectives from host code; under JAX's single-controller SPMD
model the host-side equivalent drives ``shard_map`` programs over a mesh —
one call covers all ranks at once. On multi-host (``jax.distributed``) the
same object spans processes, with XLA routing ICI/DCN.)

``HostComms`` takes rank-sharded ``jax.Array``s (axis 0 = ranks) or plain
per-rank stacks and applies the collective across the communicator axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.error import device_errors, expects
from raft_tpu.comms.comms import (MeshComms, Op, Status,
                                  status_from_exception)
from raft_tpu.resilience import fault_point


class HostComms:
    """Host-side comms over a mesh axis, mirroring ``comms_t`` usage from
    host code. Data layout contract: axis 0 of the input is the rank axis
    (length = communicator size)."""

    def __init__(self, mesh: Mesh, axis_name: str = "x"):
        expects(axis_name in mesh.axis_names, "axis %r not in mesh", axis_name)
        self.mesh = mesh
        self.axis_name = axis_name
        self.size = mesh.shape[axis_name]

    # topology (host view)
    def get_size(self) -> int:
        return self.size

    def get_rank_array(self):
        """Per-rank ranks, as a sanity probe of the SPMD identity."""
        return self._run(lambda c, x: x + c.get_rank(),
                         jnp.zeros((self.size, 1), jnp.int32))

    def comm_split(self, other_axis: str) -> "HostComms":
        """(ref: comm_split → sub-mesh axis; requires a multi-axis mesh)"""
        return HostComms(self.mesh, other_axis)

    def sync_stream(self, *arrays, nothrow: bool = False) -> Status:
        """Block on dispatched work with cancellation polling — the host-side
        sync_stream (ref: std_comms::sync_stream →
        interruptible::synchronize). Honors an armed
        :func:`raft_tpu.resilience.deadline` scope (the polling wait is
        a cancellation point). ``nothrow=True`` returns the reference's
        status vocabulary instead of raising: ABORT for a cancelled/
        deadline-expired wait, ERROR for a classified device failure —
        the ``comms_iface::sync_stream → status_t`` contract."""
        from raft_tpu.core import interruptible

        try:
            fault_point("host_sync")
            if arrays:
                with device_errors("host_comms.sync_stream"):
                    interruptible.synchronize(*arrays)
        except Exception as e:
            if nothrow:
                return status_from_exception(e)
            raise
        return Status.SUCCESS

    def barrier(self) -> None:
        """(ref: comms_iface::barrier; multi-host: sync_global_devices).
        A multi-host sync failure propagates — silently degrading to a
        local barrier would turn a distributed failure into a race.
        The local wait polls the interruptible token, so an armed
        deadline converts a hung barrier into DeadlineExceededError."""
        fault_point("host_barrier")
        try:
            from jax.experimental import multihost_utils
        except ImportError:
            multihost_utils = None
        if multihost_utils is not None and jax.process_count() > 1:
            multihost_utils.sync_global_devices("raft_tpu_barrier")
            return
        from raft_tpu.core import interruptible

        with device_errors("host_comms.barrier"):
            interruptible.synchronize(self._run(
                lambda c, x: c.barrier(x),
                jnp.zeros((self.size,), jnp.int32)))

    # -- machinery ---------------------------------------------------------
    def _sharding(self, rest_ndim: int):
        spec = P(self.axis_name, *([None] * rest_ndim))
        return NamedSharding(self.mesh, spec)

    def _run(self, fn, x, out_extra_rank: int = 0):
        """shard_map ``fn(MeshComms, shard)`` over the rank axis. The
        per-shard output rank is (x.ndim − 1) + out_extra_rank (collectives
        like allgather add one axis). Carries the ``host_collective``
        fault site — one injection hook covers every host-driven
        collective."""
        fault_point("host_collective")
        x = jnp.asarray(x)
        expects(x.shape[0] == self.size,
                "HostComms: axis 0 (=%d) must equal comm size %d",
                x.shape[0], self.size)
        comms = MeshComms(self.axis_name, size=self.size)
        in_spec = P(self.axis_name, *([None] * (x.ndim - 1)))
        out_spec = P(self.axis_name,
                     *([None] * (x.ndim - 1 + out_extra_rank)))

        def shard_fn(xs):
            return fn(comms, xs[0])[None]

        return jax.shard_map(shard_fn, mesh=self.mesh, in_specs=(in_spec,),
                             out_specs=out_spec)(x)

    # -- collectives (axis 0 = rank) ----------------------------------------
    def allreduce(self, x, op: Op = Op.SUM):
        return self._run(lambda c, s: c.allreduce(s, op), x)

    def bcast(self, x, root: int = 0):
        return self._run(lambda c, s: c.bcast(s, root), x)

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        return self._run(lambda c, s: c.reduce(s, root, op), x)

    def allgather(self, x):
        return self._run(lambda c, s: c.allgather(s), x, out_extra_rank=1)

    def gather(self, x, root: int = 0):
        return self._run(lambda c, s: c.gather(s, root), x, out_extra_rank=1)

    def allgatherv(self, x, counts: Sequence[int]):
        counts = tuple(int(c) for c in counts)
        return self._run(lambda c, s: c.allgatherv(s, counts), x)

    def gatherv(self, x, counts: Sequence[int], root: int = 0):
        counts = tuple(int(c) for c in counts)
        return self._run(lambda c, s: c.gatherv(s, counts, root), x)

    def reducescatter(self, x, op: Op = Op.SUM):
        return self._run(lambda c, s: c.reducescatter(s, op), x)

    def device_sendrecv(self, x, shift: int = 1):
        return self._run(lambda c, s: c.device_sendrecv(s, shift), x)

    def device_multicast_sendrecv(self, x):
        return self._run(lambda c, s: c.device_multicast_sendrecv(s), x,
                         out_extra_rank=1)

    # -- host point-to-point (ref: core/comms.hpp:130-140) -------------------
    def _rank_devices(self):
        """Rank → device along the communicator axis, fixing the other
        mesh axes at index 0. Host p2p addresses one rank LINE: on a
        multi-axis mesh whose lines cross process boundaries
        differently per row, build the p2p comm on a 1-D (sub)mesh of
        the actual line instead — process ownership is derived from
        these devices."""
        names = list(self.mesh.axis_names)
        ax = names.index(self.axis_name)
        dev = self.mesh.devices
        sl = [0] * dev.ndim
        sl[ax] = slice(None)
        return list(dev[tuple(sl)].flat)

    def _p2p_comm(self):
        from raft_tpu.comms import p2p

        devs = self._rank_devices()
        return devs, p2p.comm_fingerprint(devs, self.axis_name)

    def isend(self, x, src: int, dst: int, tag: int = 0):
        """Host send rank src → dst; complete via :meth:`waitall`.
        Deviation from the reference's implicit-source signature: the
        single controller drives all local ranks, so src is explicit
        (see comms/p2p.py)."""
        from raft_tpu.comms import p2p

        devs, comm = self._p2p_comm()
        return p2p.isend(devs, x, src, dst, tag, comm=comm)

    def irecv(self, shape, dtype, src: int, dst: int, tag: int = 0):
        from raft_tpu.comms import p2p

        devs, comm = self._p2p_comm()
        return p2p.irecv(devs, shape, dtype, src, dst, tag, comm=comm)

    def waitall(self, requests, timeout: float = 60.0) -> Status:
        from raft_tpu.comms import p2p

        return p2p.waitall(requests, timeout=timeout)
