"""The comms vocabulary — RAFT's ``comms_t`` re-imagined for the TPU mesh.

(ref: cpp/include/raft/core/comms.hpp:25-26 ``datatype_t``/``op_t`` enums,
:115-226 ``comms_iface`` (size/rank/comm_split/barrier/sync_stream, host
isend/irecv/waitall, collectives {allreduce, bcast, reduce, allgather,
allgatherv, gather, gatherv, reducescatter}, device p2p {device_send,
device_recv, device_sendrecv, device_multicast_sendrecv},
group_start/group_end), :234 typed proxy ``comms_t``.)

TPU-native mapping (SURVEY §2.11): a communicator is a NAMED MESH AXIS.
Collectives lower to ``jax.lax`` collectives over ICI when called inside a
``shard_map``-traced region — the SPMD analog of every rank calling
``ncclAllReduce`` on its stream. ``comm_split`` with a static color becomes
axis selection on a reshaped mesh (sub-communicators are the other axes of
a 2-D+ mesh, the reference's row/col ``subcomm`` pattern). Host p2p and
group_start/end exist for API parity: inside one traced SPMD program,
grouping is XLA's job, and p2p is ``ppermute``.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class DataType(enum.Enum):
    """(ref: core/comms.hpp:25 ``datatype_t``)"""

    CHAR = "int8"
    UINT8 = "uint8"
    INT32 = "int32"
    UINT32 = "uint32"
    INT64 = "int64"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BFLOAT16 = "bfloat16"  # TPU addition


def get_type(x) -> DataType:
    """T → datatype_t. (ref: core/comms.hpp ``get_type<T>()``)"""
    return DataType(str(jnp.asarray(x).dtype))


class Op(enum.Enum):
    """(ref: core/comms.hpp:26 ``op_t``)"""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


class Status(enum.Enum):
    """(ref: core/comms.hpp ``status_t`` — returned by sync_stream)"""

    SUCCESS = 0
    ERROR = 1
    ABORT = 2


def status_from_exception(exc: BaseException) -> Status:
    """Map a failure observed while waiting on collective work to the
    reference's ``status_t`` vocabulary — the NCCL abort/timeout
    semantics table (docs/MIGRATION.md): a cancellation or expired
    deadline is ``ABORT`` (the communicator was torn down on purpose,
    like ``ncclCommAbort``); any classified device failure is
    ``ERROR`` (the reference's ``commStatus_t`` error path). Used by
    ``HostComms.sync_stream(nothrow=True)``."""
    from raft_tpu.core.error import DeadlineExceededError
    from raft_tpu.core.interruptible import InterruptedException

    if isinstance(exc, (DeadlineExceededError, InterruptedException)):
        return Status.ABORT
    return Status.ERROR


def _count(collective: str, x, axis_name) -> None:
    """Report one collective to the metrics registry (lazy import keeps
    the comms module importable without observability and vice versa).
    Fires at trace time — see hooks.record_collective for the contract."""
    from raft_tpu.observability import record_collective

    record_collective(collective, x, axis_name)


def _psum_like(x, op: Op, axis_name):
    if op == Op.SUM:
        return jax.lax.psum(x, axis_name)
    if op == Op.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == Op.MIN:
        return jax.lax.pmin(x, axis_name)
    # PROD via exp/log is lossy; use all_gather+prod (small arrays) instead
    gathered = jax.lax.all_gather(x, axis_name)
    return jnp.prod(gathered, axis=0)


class MeshComms:
    """SPMD communicator over a named mesh axis — valid inside a
    ``shard_map`` region whose mesh carries ``axis_name``.

    Each method is the traced-per-shard analog of the reference's
    per-rank comms call (ref: comms/detail/std_comms.hpp collectives →
    NCCL; here → XLA collectives over ICI).
    """

    def __init__(self, axis_name: str, size: Optional[int] = None):
        self.axis_name = axis_name
        self._size = size

    # -- topology ---------------------------------------------------------
    def get_size(self):
        """(ref: comms_iface::get_size)"""
        if self._size is not None:
            return self._size
        return jax.lax.axis_size(self.axis_name)

    def get_rank(self):
        """(ref: comms_iface::get_rank)"""
        return jax.lax.axis_index(self.axis_name)

    def comm_split(self, other_axis: str, size: Optional[int] = None) -> "MeshComms":
        """Sub-communicator along another mesh axis: ranks sharing this
        axis's index form the new clique. Pass ``size`` to keep the static
        size (needed by p2p's permutation table).
        (ref: comms_iface::comm_split via ncclCommSplit; here: pick the
        other axis of the 2-D mesh.)"""
        return MeshComms(other_axis, size=size)

    def comm_split_color(self, color, key=None) -> "ColorComms":
        """Arbitrary-color split — the reference's full
        ``comm_split(color, key)`` semantics (ref: core/comms.hpp:123):
        ranks with equal ``color`` form a clique, ordered by
        ``(key, rank)``. ``color``/``key`` may be traced per-rank values.
        Static axis splits (row/col grids) should prefer :meth:`comm_split`
        — it lowers to pure ICI collectives; ColorComms collectives ride an
        axis-wide all_gather + masked fold (see ColorComms docs)."""
        return ColorComms(self, color, key)

    def barrier(self, token=None):
        """SPMD barrier: a zero-cost psum dependency.
        (ref: comms_iface::barrier)"""
        t = jnp.zeros((), jnp.int32) if token is None else token
        return jax.lax.psum(t, self.axis_name)

    def sync_stream(self, *arrays) -> Status:
        """Inside a traced region this is a no-op (XLA orders the program);
        kept for vocabulary parity. (ref: comms_iface::sync_stream)"""
        return Status.SUCCESS

    # -- collectives -------------------------------------------------------
    def allreduce(self, x, op: Op = Op.SUM):
        """(ref: comms_iface::allreduce → ncclAllReduce)"""
        _count("allreduce", x, self.axis_name)
        return _psum_like(x, op, self.axis_name)

    def bcast(self, x, root: int = 0):
        """Broadcast from root as masked psum — O(|x|) memory per device,
        no [size, |x|] all-gather transient. (ref: comms_iface::bcast(2))"""
        _count("bcast", x, self.axis_name)
        is_root = jax.lax.axis_index(self.axis_name) == root
        masked = jnp.where(is_root, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, self.axis_name)

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        """Root gets the reduction; non-root ranks get their INPUT back
        unchanged — the reference's in-place reduce leaves non-root
        buffers untouched and its test asserts only the root
        (comms_iface::reduce, detail/test.hpp:97-124)."""
        _count("reduce", x, self.axis_name)
        full = _psum_like(x, op, self.axis_name)
        is_root = jax.lax.axis_index(self.axis_name) == root
        return jnp.where(is_root, full, x)

    def allgather(self, x):
        """(ref: comms_iface::allgather)"""
        _count("allgather", x, self.axis_name)
        return jax.lax.all_gather(x, self.axis_name)

    def allgatherv(self, x, counts: Sequence[int]):
        """Variable-size allgather: shards are padded to max(counts) by the
        caller; this returns the concatenation with padding stripped.
        (ref: comms_iface::allgatherv — static counts, like the reference's
        host-provided recvcounts.)"""
        _count("allgatherv", x, self.axis_name)
        return self._allgatherv_impl(x, counts)

    def _allgatherv_impl(self, x, counts: Sequence[int]):
        gathered = jax.lax.all_gather(x, self.axis_name)  # [size, maxlen, ...]
        parts = [gathered[i, : counts[i]] for i in range(len(counts))]
        return jnp.concatenate(parts, axis=0)

    def gather(self, x, root: int = 0):
        """(ref: comms_iface::gather; non-root gets zeros)"""
        _count("gather", x, self.axis_name)
        gathered = jax.lax.all_gather(x, self.axis_name)
        is_root = jax.lax.axis_index(self.axis_name) == root
        return jnp.where(is_root, gathered, jnp.zeros_like(gathered))

    def gatherv(self, x, counts: Sequence[int], root: int = 0):
        """(ref: comms_iface::gatherv)"""
        _count("gatherv", x, self.axis_name)
        out = self._allgatherv_impl(x, counts)
        is_root = jax.lax.axis_index(self.axis_name) == root
        return jnp.where(is_root, out, jnp.zeros_like(out))

    def reducescatter(self, x, op: Op = Op.SUM):
        """Each rank gets its slice of the reduction.
        (ref: comms_iface::reducescatter)"""
        expects(op == Op.SUM, "reducescatter: SUM only (like psum_scatter)")
        _count("reducescatter", x, self.axis_name)
        return jax.lax.psum_scatter(x, self.axis_name, tiled=True)

    # -- device p2p ---------------------------------------------------------
    def device_send(self, x, dst: int):
        """Paired send/recv become one ppermute — see device_sendrecv.
        Counted under its OWN collective label (with payload bytes), so
        metrics exporters can tell explicit p2p sends apart from the
        generic sendrecv surface. (ref: comms_iface::device_send)"""
        _count("device_send", x, self.axis_name)
        return self._sendrecv_impl(x, dst)

    def device_recv(self, x_from_permute):
        return x_from_permute

    def device_sendrecv(self, x, dst, src=None):
        """Send shard to ``dst`` while receiving from whoever targets us.
        dst may be an int (uniform shift pattern) or a list of (src, dst)
        pairs. (ref: comms_iface::device_sendrecv → here ppermute on ICI)"""
        _count("sendrecv", x, self.axis_name)
        return self._sendrecv_impl(x, dst)

    def _sendrecv_impl(self, x, dst):
        size = self._size
        expects(size is not None,
                "device_sendrecv needs MeshComms(axis, size=...) for the "
                "static permutation table")
        if isinstance(dst, int):
            perm = [(i, (i + dst) % size) for i in range(size)]
        else:
            perm = list(dst)
        return jax.lax.ppermute(x, self.axis_name, perm)

    def collective_permute(self, x, perm: Sequence[Tuple[int, int]]):
        """Explicit-permutation exchange — ``jax.lax.ppermute`` with the
        caller's (src, dst) table, counted (calls + payload bytes) under
        its own ``collective_permute`` label so the sharded-KNN
        tournament merge rounds are visible in the metrics exporters.
        Ranks no pair targets receive ppermute's zero fill.
        (ref: ncclSend/ncclRecv groups — the reference's p2p rendering
        of a butterfly exchange.)"""
        _count("collective_permute", x, self.axis_name)
        return jax.lax.ppermute(x, self.axis_name, list(perm))

    def device_multicast_sendrecv(self, x, dsts: Optional[Sequence[int]] = None):
        """One shard to many ranks: all_gather then select is the XLA-native
        multicast. (ref: comms_iface::device_multicast_sendrecv)"""
        _count("multicast_sendrecv", x, self.axis_name)
        return jax.lax.all_gather(x, self.axis_name)

    # -- grouping -----------------------------------------------------------
    def group_start(self):
        """No-op: XLA fuses/schedules collectives inside one program.
        (ref: comms_iface::group_start)"""

    def group_end(self):
        """(ref: comms_iface::group_end)"""


class ColorComms:
    """Dynamic sub-communicator over an arbitrary color partition.

    (ref: core/comms.hpp:123 ``comm_split(color, key)`` — NCCL regroups
    ranks into new cliques at runtime. XLA collectives are compiled over
    STATIC axes, so the TPU rendering keeps the parent axis and makes
    membership a data plane concept: every collective is an axis-wide
    ``all_gather`` followed by a masked fold over ranks whose color equals
    the caller's. Correct for any traced color/key assignment; costs
    O(parent_size·|x|) per call, so it is the general-case path — static
    grid splits should use mesh axes (``comm_split``), which lower to
    plain psum/ppermute.)

    Valid inside a ``shard_map`` region over the parent communicator's
    mesh axis. Gather-family outputs are sized by the PARENT axis (static
    shapes): the first ``get_size()`` rows are the clique's values in
    (key, rank) order, the rest are zero-padding.
    """

    def __init__(self, parent: MeshComms, color, key=None):
        self.parent = parent
        self.axis_name = parent.axis_name
        self.color = jnp.asarray(color, jnp.int32)
        rank = parent.get_rank()
        self.key = rank if key is None else jnp.asarray(key, jnp.int32)
        # gathered per-rank tables, [parent_size]
        self._colors = jax.lax.all_gather(self.color, self.axis_name)
        self._keys = jax.lax.all_gather(self.key, self.axis_name)
        self._member = self._colors == self.color
        n = self._colors.shape[0]
        order = jnp.arange(n, dtype=jnp.int32)
        # single source of truth for the (key, rank) ordering: the rank of
        # parent-rank r within ITS clique; own rank/size derive from it
        same = (self._colors[None, :] == self._colors[:, None])
        lt = ((self._keys[None, :] < self._keys[:, None])
              | ((self._keys[None, :] == self._keys[:, None])
                 & (order[None, :] < order[:, None])))
        self._subrank_of = jnp.sum((same & lt).astype(jnp.int32), axis=1)
        self._rank = self._subrank_of[rank]
        self._size = jnp.sum(self._member.astype(jnp.int32))

    # -- topology -----------------------------------------------------------
    def get_size(self):
        """Clique size (traced). (ref: comms_iface::get_size)"""
        return self._size

    def get_rank(self):
        """Rank within the clique, (key, rank)-ordered.
        (ref: comms_iface::get_rank)"""
        return self._rank

    # -- machinery ----------------------------------------------------------
    def _gather_members(self, x):
        """[parent_size, ...] of every rank's x, with a member mask."""
        x = jnp.asarray(x)
        g = jax.lax.all_gather(x, self.axis_name)
        mask = self._member.reshape((-1,) + (1,) * x.ndim)
        return g, mask

    # -- collectives (within the clique) ------------------------------------
    def allreduce(self, x, op: Op = Op.SUM):
        g, mask = self._gather_members(x)
        if op == Op.SUM:
            return jnp.sum(jnp.where(mask, g, 0), axis=0)
        if op == Op.PROD:
            return jnp.prod(jnp.where(mask, g, 1), axis=0)
        # dtype-aware identities: an inf fill would silently promote
        # integer inputs to f32 (lossy past 2^24)
        if jnp.issubdtype(g.dtype, jnp.integer):
            lo, hi = jnp.iinfo(g.dtype).min, jnp.iinfo(g.dtype).max
        else:
            lo, hi = -jnp.inf, jnp.inf
        if op == Op.MIN:
            return jnp.min(jnp.where(mask, g, hi), axis=0)
        return jnp.max(jnp.where(mask, g, lo), axis=0)

    def bcast(self, x, root: int = 0):
        """Value of the clique member with subcomm rank ``root``."""
        g, mask = self._gather_members(x)
        sel = (self._subrank_of == root).reshape(mask.shape) & mask
        return jnp.sum(jnp.where(sel, g, 0), axis=0)

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        """Non-root gets its input back — see MeshComms.reduce."""
        full = self.allreduce(x, op)
        return jnp.where(self._rank == root, full, jnp.asarray(x))

    def allgather(self, x):
        """[parent_size, ...]: rows [0, get_size()) hold the clique's
        values in subcomm-rank order; the tail is zeros. Linear cost: one
        axis gather + a row scatter."""
        g, _ = self._gather_members(x)
        n = g.shape[0]
        slot = jnp.where(self._member, self._subrank_of, n)  # n → dropped
        return jnp.zeros_like(g).at[slot].set(g, mode="drop")

    def allgatherv(self, x, counts: Sequence[int]):
        """Static per-subrank ``counts`` (like the reference's host-given
        recvcounts); shards padded to max(counts) by the caller.
        (ref: comms_iface::allgatherv)"""
        out = self.allgather(x)
        return jnp.concatenate(
            [out[i, : counts[i]] for i in range(len(counts))], axis=0)

    def gather(self, x, root: int = 0):
        out = self.allgather(x)
        return jnp.where(self._rank == root, out, jnp.zeros_like(out))

    def gatherv(self, x, counts: Sequence[int], root: int = 0):
        out = self.allgatherv(x, counts)
        return jnp.where(self._rank == root, out, jnp.zeros_like(out))

    def reducescatter(self, x, op: Op = Op.SUM, clique_size: Optional[int]
                      = None):
        """Each member gets its tile of the within-clique reduction.
        The clique size is data-dependent, but XLA slices need static
        shapes — pass the statically-known ``clique_size`` (the
        reference's recvcount plays the same role).
        (ref: comms_iface::reducescatter)"""
        expects(clique_size is not None,
                "ColorComms.reducescatter needs static clique_size "
                "(dynamic membership cannot size the output tile)")
        full = self.allreduce(x, op)
        chunk = full.shape[0] // clique_size
        return jax.lax.dynamic_slice_in_dim(
            full, self._rank * chunk, chunk, axis=0)

    def barrier(self, token=None):
        return self.parent.barrier(token)

    def sync_stream(self, *arrays) -> Status:
        """(ref: comms_iface::sync_stream — no-op inside one program)"""
        return Status.SUCCESS

    def comm_split_color(self, color, key=None) -> "ColorComms":
        """Split the clique again: combined colors keep cliques disjoint
        across parents. Colors must fit 15 bits (documented bound).
        (ref: recursive comm_split)"""
        combined = self.color * jnp.int32(32768) + (
            jnp.asarray(color, jnp.int32) & jnp.int32(32767))
        return ColorComms(self.parent, combined, key)

    # -- device p2p (subcomm ranks; zero-fill parity with ppermute) ---------
    def device_send(self, x, dst):
        """(ref: comms_iface::device_send — see device_sendrecv)"""
        return self.device_sendrecv(x, dst)

    def device_recv(self, x_from_permute):
        return x_from_permute

    def device_sendrecv(self, x, dst, src=None):
        """Same contract as :meth:`MeshComms.device_sendrecv`, in subcomm
        ranks: int ``dst`` = uniform ring shift (receive from the member
        ``dst`` subcomm-ranks behind); a list of ``(src, dst)`` pairs
        selects explicitly — members that are not a destination of any
        pair receive ZEROS, matching ppermute's fill."""
        g, _ = self._gather_members(x)
        x = jnp.asarray(x)
        if isinstance(dst, int):
            want = jnp.mod(self._rank - dst, jnp.maximum(self._size, 1))
        else:
            want = jnp.int32(-2)          # no pair targets me → zeros
            for s, d in dst:
                want = jnp.where(self._rank == d, jnp.int32(s), want)
        slot = jnp.where(self._member, self._subrank_of, -1)
        sel = (slot == want).reshape((-1,) + (1,) * x.ndim)
        return jnp.sum(jnp.where(sel, g, 0), axis=0)

    def device_multicast_sendrecv(self, x, dsts: Optional[Sequence[int]]
                                  = None):
        """(ref: comms_iface::device_multicast_sendrecv — padded
        allgather, like the MeshComms rendering)"""
        return self.allgather(x)

    # -- grouping (no-ops inside one traced program) ------------------------
    def group_start(self):
        """(ref: comms_iface::group_start)"""

    def group_end(self):
        """(ref: comms_iface::group_end)"""
