"""MNMG session — the raft-dask ``Comms`` equivalent without Dask.

(ref: python/raft-dask/raft_dask/common/comms.py:28 ``class Comms`` —
NCCL-uniqueId rendezvous + per-worker handle injection (SURVEY §3.2), and
``local_handle`` (comms.py:236). On TPU, rendezvous is
``jax.distributed.initialize`` (DCN bootstrap replacing the NCCL uniqueId
broadcast); the clique is a ``Mesh`` over all devices; injection is
``resources.set_comms`` exactly like ``inject_comms_on_handle``.)
"""

from __future__ import annotations

import uuid
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.core.error import expects
from raft_tpu.core.resources import DeviceResources, Resources
from raft_tpu.core.resource_types import ResourceType
from raft_tpu.comms.host_comms import HostComms

_sessions: dict = {}


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap. (ref: the NCCL uniqueId rendezvous in
    Comms.init / nccl.pyx:110 → here jax.distributed.initialize, which
    uses the coordinator for the same role.) No-op when single-process."""
    if coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


class Comms:
    """Session object building the communicator clique and injecting it
    into handles. (ref: raft_dask Comms.init — comms.py:161.)"""

    def __init__(self, devices: Optional[Sequence] = None,
                 axis_names: Tuple[str, ...] = ("x",),
                 mesh_shape: Optional[Tuple[int, ...]] = None,
                 verbose: bool = False):
        self.session_id = uuid.uuid4().hex[:16]
        self._devices = list(devices) if devices is not None else jax.devices()
        self.axis_names = axis_names
        self.mesh_shape = mesh_shape
        self.mesh: Optional[Mesh] = None
        self.handle: Optional[DeviceResources] = None
        self.nccl_initialized = False  # vocabulary parity
        self.ucx_initialized = False

    def init(self, workers: Optional[Sequence] = None) -> None:
        """Build the mesh + comms and inject into a fresh handle.
        (ref: comms.py:161 ``Comms.init`` → _func_init_all per worker;
        single-controller SPMD needs one handle for the whole clique.)"""
        devs = list(workers) if workers is not None else self._devices
        n = len(devs)
        shape = self.mesh_shape if self.mesh_shape is not None else (n,)
        expects(int(np.prod(shape)) == n,
                "Comms.init: mesh shape %s != device count %d", shape, n)
        self.mesh = Mesh(np.array(devs).reshape(shape), self.axis_names)
        self.handle = DeviceResources(device=devs[0])
        self.handle.set_mesh(self.mesh)
        primary = HostComms(self.mesh, self.axis_names[0])
        self.handle.set_comms(primary)
        # sub-communicators for every additional mesh axis
        # (ref: resource::set_subcomm, core/resource/sub_comms.hpp)
        for ax in self.axis_names[1:]:
            self.handle.set_subcomm(ax, HostComms(self.mesh, ax))
        self.handle.set_resource(ResourceType.ROOT_RANK, 0)
        self.nccl_initialized = True
        _sessions[self.session_id] = self

    def destroy(self) -> None:
        """(ref: comms.py:209 ``Comms.destroy`` — elasticity model: tear
        down and re-create after cluster changes.)"""
        _sessions.pop(self.session_id, None)
        self.mesh = None
        self.handle = None
        self.nccl_initialized = False

    @property
    def comms(self) -> HostComms:
        expects(self.handle is not None, "Comms not initialized")
        return self.handle.get_comms()


def local_handle(session_id: str) -> Optional[DeviceResources]:
    """Fetch the session's injected handle. (ref: comms.py:236
    ``local_handle(sessionId)``)"""
    s = _sessions.get(session_id)
    return s.handle if s else None


def inject_comms_on_handle(handle: Resources, mesh: Mesh,
                           axis_name: str = "x",
                           subcomm_axes: Sequence[str] = ()) -> None:
    """(ref: python/raft-dask/.../comms_utils.pyx:248,278
    ``inject_comms_on_handle[_coll_only]``)"""
    handle.set_mesh(mesh)
    handle.set_comms(HostComms(mesh, axis_name))
    for ax in subcomm_axes:
        handle.set_subcomm(ax, HostComms(mesh, ax))
