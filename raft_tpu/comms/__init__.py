"""raft_tpu.comms — the NCCL/UCX comms vocabulary over jax.lax collectives.
(ref: cpp/include/raft/comms + core/comms.hpp, SURVEY §2.11/§3.2.)"""

from raft_tpu.comms.comms import ColorComms, DataType, Op, Status, MeshComms, get_type
from raft_tpu.comms.host_comms import HostComms
from raft_tpu.comms.session import (
    Comms,
    initialize_distributed,
    inject_comms_on_handle,
    local_handle,
)
from raft_tpu.comms import test_battery
from raft_tpu.comms.mpi import detect_mpi_environment, initialize_mpi_comms

__all__ = [
    "ColorComms", "DataType", "Op", "Status", "MeshComms", "HostComms", "get_type",
    "Comms", "initialize_distributed", "inject_comms_on_handle",
    "local_handle", "test_battery", "detect_mpi_environment",
    "initialize_mpi_comms",
]
