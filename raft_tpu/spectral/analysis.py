"""Partition / modularity analysis + spectral embedding.

(ref: cpp/include/raft/spectral/partition.cuh:38 ``analyzePartition``
(edge-cut + cost via indicator vectors, detail/partition.hpp:81-85),
modularity_maximization.cuh:31 ``analyzeModularity``. The eigensolver+
kmeans *clustering* driver left for cuVS; what remains — and is rebuilt
here — is the analysis plus the BASELINE "spectral embedding" pipeline:
``compute_graph_laplacian`` + ``lanczos_compute_eigenpairs`` (SURVEY §2.6).)
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.spectral.matrix_wrappers import LaplacianMatrix, ModularityMatrix

Sparse = Union[COOMatrix, CSRMatrix]


def analyze_partition(res, A: Sparse, n_clusters: int, clusters
                      ) -> Tuple[float, float]:
    """Returns (edge_cut, cost); cost = Σ_i cut(i)/|cluster_i|.
    (ref: spectral/partition.cuh:38 ``analyzePartition``)"""
    clusters = jnp.asarray(clusters)
    L = LaplacianMatrix(res, A)
    dtype = L.diagonal.dtype
    edge_cut = jnp.asarray(0.0, dtype)
    cost = jnp.asarray(0.0, dtype)
    for i in range(n_clusters):
        w = (clusters == i).astype(dtype)
        size = jnp.sum(w)
        part_cut = jnp.dot(w, L.mv(w))
        nonempty = size > 0
        cost = cost + jnp.where(nonempty, part_cut / jnp.where(nonempty, size, 1.0), 0.0)
        edge_cut = edge_cut + jnp.where(nonempty, part_cut / 2.0, 0.0)
    return float(edge_cut), float(cost)


def analyze_modularity(res, A: Sparse, n_clusters: int, clusters) -> float:
    """Modularity = Σ_i w_iᵀ B w_i / ‖d‖₁.
    (ref: modularity_maximization.cuh:31 ``analyzeModularity``;
    detail normalizes by the L1 norm of the degree vector = 2m.)"""
    clusters = jnp.asarray(clusters)
    B = ModularityMatrix(res, A)
    dtype = B.degree.dtype
    total = jnp.asarray(0.0, dtype)
    for i in range(n_clusters):
        w = (clusters == i).astype(dtype)
        total = total + jnp.dot(w, B.mv(w))
    return float(total / B.edge_sum)


def fit_embedding(res, A: Sparse, n_components: int, ncv=None,
                  tolerance: float = 1e-5, max_iterations: int = 2000,
                  seed: int = 42, drop_first: bool = True,
                  normalized: bool = True, jit_loop=None,
                  tiled="auto", mesh=None, mesh_axis: str = "x"):
    """Spectral embedding: smallest eigenvectors of the graph Laplacian.

    The BASELINE config-4 pipeline (COO Laplacian + Lanczos). Returns
    (eigenvalues, embedding [n, n_components]).

    ``tiled``: "auto" converts the Laplacian to the tiled-ELL layout
    (one-time host pass) so the Lanczos hot loop runs the Pallas SpMV
    kernel — on TPU, for graphs past ~200k nonzeros; True/False force
    either path.

    ``mesh``: a ``jax.sharding.Mesh`` makes the solve MNMG — the
    Laplacian's rows are sharded over ``mesh[mesh_axis]`` and the
    Lanczos matvec runs as a ``shard_map`` of the per-block Pallas
    SpMV (sparse/sharded.py; the reference's comms-injected MNMG
    pipeline — core/comms.hpp:234 usage model). Results match the
    single-device solve (tested on the 8-device virtual mesh).
    """
    from raft_tpu.sparse.linalg import (
        compute_graph_laplacian, laplacian_normalized, prepare_spmv)
    from raft_tpu.sparse.solver.lanczos import lanczos_compute_eigenpairs
    from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig

    k = n_components + (1 if drop_first else 0)
    if normalized:
        L, _ = laplacian_normalized(res, A)
    else:
        L = compute_graph_laplacian(res, A)
    if tiled not in ("auto", True, False):
        raise ValueError(
            f"fit_embedding: tiled must be 'auto', True or False, "
            f"got {tiled!r}")
    if mesh is not None:
        from raft_tpu.sparse.sharded import shard_spmv_operand

        if tiled is False:
            raise ValueError(
                "fit_embedding: tiled=False conflicts with mesh= — the "
                "MNMG path IS the sharded tiled-ELL operand")
        if L.values.dtype == jnp.float64:
            raise ValueError(
                "fit_embedding: mesh= computes in f32 (tiled kernels); "
                "cast the input or drop mesh for the f64 CSR path")
        L = shard_spmv_operand(L, mesh, axis=mesh_axis)
    else:
        if tiled == "auto":
            # f64 inputs stay on the CSR path (the tiled kernel computes
            # in f32 — see the dtype policy in linalg.spmm's docstring)
            tiled = (jax.default_backend() == "tpu" and L.nnz >= 200_000
                     and L.values.dtype == jnp.float32)
        if tiled:
            L = prepare_spmv(L)
    # jit_loop=True compiles the whole solve into one program (best for
    # remote/tunneled devices); the host loop (default) keeps cancellation
    # points and the stagnation early-exit for large zero clusters
    config = LanczosSolverConfig(
        n_components=k, max_iterations=max_iterations, ncv=ncv,
        tolerance=tolerance, which=LANCZOS_WHICH.SA, seed=seed,
        jit_loop=jit_loop)
    vals, vecs = lanczos_compute_eigenpairs(res, L, config)
    if drop_first:
        return vals[1:], vecs[:, 1:]
    return vals, vecs
