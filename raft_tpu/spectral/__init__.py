"""raft_tpu.spectral — graph spectral analysis. (ref:
cpp/include/raft/spectral, SURVEY §2.6.)"""

from raft_tpu.spectral.matrix_wrappers import (
    SparseMatrix,
    LaplacianMatrix,
    ModularityMatrix,
)
from raft_tpu.spectral.analysis import (
    analyze_partition,
    analyze_modularity,
    fit_embedding,
)

__all__ = [
    "SparseMatrix", "LaplacianMatrix", "ModularityMatrix",
    "analyze_partition", "analyze_modularity", "fit_embedding",
]
