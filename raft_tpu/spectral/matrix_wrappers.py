"""Materialization-free spectral operators.

(ref: cpp/include/raft/spectral/detail/matrix_wrappers.hpp —
``sparse_matrix_t:132`` (CSR view + ``mv()`` dispatch,
``sparse_mv_alg_t:64``), ``laplacian_matrix_t:325`` (L·x = D·x − A·x
without materializing L), ``modularity_matrix_t:400``
(B·x = A·x − (d·x)·d / 2m).)
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.linalg import spmv

Sparse = Union[COOMatrix, CSRMatrix]


class SparseMatrix:
    """CSR/COO wrapper with ``mv``. (ref: matrix_wrappers.hpp:132)"""

    def __init__(self, res, A: Sparse):
        self.res = res
        self.A = A
        self.shape = A.shape

    def mv(self, x, alpha=1.0, beta=0.0, y=None):
        out = alpha * spmv(self.res, self.A, x)
        if y is not None and beta != 0.0:
            out = out + beta * jnp.asarray(y)
        return out


class LaplacianMatrix(SparseMatrix):
    """L·x = D·x − A·x, degree precomputed, L never materialized.
    (ref: matrix_wrappers.hpp:325 ``laplacian_matrix_t``)"""

    def __init__(self, res, A: Sparse):
        super().__init__(res, A)
        ones = jnp.ones((A.shape[1],), A.values.dtype)
        self.diagonal = spmv(res, A, ones)  # degree vector

    def mv(self, x, alpha=1.0, beta=0.0, y=None):
        lx = self.diagonal * x - spmv(self.res, self.A, x)
        out = alpha * lx
        if y is not None and beta != 0.0:
            out = out + beta * jnp.asarray(y)
        return out


class ModularityMatrix(SparseMatrix):
    """B·x = A·x − (d·x)·d / 2m. (ref: matrix_wrappers.hpp:400
    ``modularity_matrix_t``)"""

    def __init__(self, res, A: Sparse):
        super().__init__(res, A)
        ones = jnp.ones((A.shape[1],), A.values.dtype)
        self.degree = spmv(res, A, ones)
        self.edge_sum = jnp.sum(self.degree)  # = 2m for symmetric A

    def mv(self, x, alpha=1.0, beta=0.0, y=None):
        bx = spmv(self.res, self.A, x) - \
            (jnp.dot(self.degree, x) / self.edge_sum) * self.degree
        out = alpha * bx
        if y is not None and beta != 0.0:
            out = out + beta * jnp.asarray(y)
        return out
