"""Rank-sharded SpMV — the MNMG tier for the sparse solver stack.

(ref: the reference makes any primitive comms-capable by injecting a
``comms_t`` into the handle — core/comms.hpp:234 usage model,
docs/source/using_raft_comms.rst — and its Lanczos hot loop is the SpMV
at sparse/solver/detail/lanczos.cuh:248. The MNMG decomposition there is
1-D row partitioning with an allgather of the matvec result.)

TPU-first design: instead of per-rank processes + NCCL, the partitioned
matrix is ONE jittable operand — the tiled-ELL layout of each contiguous
row block, padded to a common chunk geometry and stacked on a leading
mesh axis. ``spmv_sharded`` is a ``jax.shard_map`` over that axis: each
device runs the UNCHANGED single-device Pallas SpMV pipeline
(ops/spmv_pallas.spmv_tiled) on its block against a replicated x and the
row blocks concatenate into y — XLA inserts the all-gather when a
downstream consumer (the replicated Lanczos recurrence) needs the full
vector, riding ICI. No solver code changes: the operand dispatches
through the same ``sparse.linalg.spmv`` entry the single-device layouts
use, so ``lanczos_compute_eigenpairs`` / ``fit_embedding`` become MNMG
by swapping the operand.

Why padding is sound (the invariants come from ops/spmv_pallas):
- gather side: pad chunks carry vals=0 → zero contributions; their
  chunk_col_tile=0 is a valid x tile.
- bridge: every row of the padded gather stream beyond a shard's true
  n_gather is all-zero, so stale zero-row pointers (tile_csr points
  pads at the old appended-zero-row index) keep reading zeros.
- scatter side: pad slots carry row_local=R (matches nothing); pad
  chunks repeat the last real chunk_row_tile so the kernel's
  first-visit test never re-zeroes a written tile.
- unvisited row tiles are zeroed by the per-shard visited mask.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.tiled import TiledELL, tile_csr
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedTiledELL:
    """P row blocks of one sparse matrix, each a tiled-ELL layout with
    identical (padded) chunk geometry, stacked on the leading axis and
    sharded over ``mesh[axis]``. Accepted by ``sparse.linalg.spmv`` and
    the Lanczos/spectral solvers."""

    shape: Tuple[int, int] = dataclasses.field(metadata=dict(static=True))
    rb: int = dataclasses.field(metadata=dict(static=True))  # rows/shard
    C: int = dataclasses.field(metadata=dict(static=True))
    R: int = dataclasses.field(metadata=dict(static=True))
    E: int = dataclasses.field(metadata=dict(static=True))
    n_col_tiles: int = dataclasses.field(metadata=dict(static=True))
    n_row_tiles: int = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    # stacked leaves, leading axis = shard
    vals: jax.Array             # [Pn, NC, E] f32
    col_local: jax.Array        # [Pn, NC, E] int32
    chunk_col_tile: jax.Array   # [Pn, NC] int32
    perm_rows: jax.Array        # [Pn, NM/8] int32
    row_local: jax.Array        # [Pn, MC, E] int32
    chunk_row_tile: jax.Array   # [Pn, MC] int32
    visited_row_tiles: jax.Array  # [Pn, n_row_tiles] bool

    @property
    def n_shards(self) -> int:
        return self.vals.shape[0]

    @property
    def nnz(self) -> int:  # pad-inclusive stream size, like TiledELL
        return int(np.prod(self.vals.shape[1:]))


def _pad_axis0(a: np.ndarray, n: int, fill) -> np.ndarray:
    if a.shape[0] == n:
        return a
    pad = np.full((n - a.shape[0],) + a.shape[1:], fill, a.dtype)
    return np.concatenate([a, pad], axis=0)


def shard_spmv_operand(A, mesh: Mesh, axis: str = "x",
                       C: int = 512, R: int = 256, E: int = 2048,
                       ) -> ShardedTiledELL:
    """One-time conversion: partition ``A``'s rows into ``mesh[axis]``
    contiguous R-aligned blocks, tile each (host pass), pad to common
    chunk geometry, and place the stack sharded over the mesh axis.

    The sharded sibling of :func:`raft_tpu.sparse.linalg.prepare_spmv`
    (ref: the raft-dask pattern of partitioning once at fit time)."""
    expects(axis in mesh.shape, "shard_spmv_operand: mesh has no axis %s",
            axis)
    vals_dtype = (A.values.dtype if hasattr(A, "values") else None)
    if vals_dtype is not None and jnp.dtype(vals_dtype).itemsize > 4:
        # the tiled kernels compute in f32 (dtype policy, linalg.spmm);
        # silently downcasting an f64 solve would break tolerances the
        # caller asked for — make the cast explicit at the call site
        raise ValueError(
            "shard_spmv_operand: tiled kernels compute in f32; cast the "
            "matrix explicitly (or run the single-device CSR path for "
            "f64 solves)")
    n_shards = int(mesh.shape[axis])
    if isinstance(A, CSRMatrix):
        rows = np.asarray(A.row_ids())
        cols, vals, shape = (np.asarray(A.indices), np.asarray(A.values),
                             A.shape)
    elif isinstance(A, COOMatrix):
        rows, cols, vals, shape = (np.asarray(A.rows), np.asarray(A.cols),
                                   np.asarray(A.values), A.shape)
    else:
        raise TypeError(f"shard_spmv_operand: expected sparse matrix, "
                        f"got {type(A)}")
    n_rows, n_cols = shape
    rb = -(-n_rows // (n_shards * R)) * R      # R-aligned rows per shard
    shards = []
    for p in range(n_shards):
        lo, hi = p * rb, (p + 1) * rb
        m = (rows >= lo) & (rows < hi)
        t = tile_csr(COOMatrix(
            jnp.asarray(rows[m] - lo, jnp.int32),
            jnp.asarray(cols[m], jnp.int32),
            jnp.asarray(vals[m], jnp.float32), (rb, n_cols)),
            C=C, R=R, E=E, impl="numpy")
        expects(t.perm_rows is not None,
                "shard_spmv_operand: need the 8-aligned bucket layout")
        shards.append(t)
    NC = max(t.n_chunks for t in shards)
    MC = max(t.m_chunks for t in shards)
    stacked = {}
    for name, fill in (("vals", 0.0), ("col_local", 0), ("row_local", 0)):
        arrs = []
        for t in shards:
            a = np.asarray(getattr(t, name))
            n = NC if name in ("vals", "col_local") else MC
            # scatter pad slots must match nothing: row_local pad = R
            arrs.append(_pad_axis0(a, n, fill if name != "row_local"
                                   else R))
        stacked[name] = np.stack(arrs)
    stacked["chunk_col_tile"] = np.stack([
        _pad_axis0(np.asarray(t.chunk_col_tile), NC, 0) for t in shards])
    crt = []
    for t in shards:
        a = np.asarray(t.chunk_row_tile)
        # repeat the last real tile id so the scatter kernel's
        # first-visit test stays False through the pad chunks
        last = a[-1] if a.shape[0] else np.int32(0)
        crt.append(_pad_axis0(a, MC, last))
    stacked["chunk_row_tile"] = np.stack(crt)
    stacked["perm_rows"] = np.stack([
        # point pads at the appended zero row of the PADDED stream
        _pad_axis0(np.asarray(t.perm_rows), MC * E // 8, NC * E // 8)
        for t in shards])
    stacked["visited_row_tiles"] = np.stack(
        [np.asarray(t.visited_row_tiles) for t in shards])

    # make_array_from_callback (not device_put): under a multi-process
    # mesh each process can only place its ADDRESSABLE shards — every
    # process runs this same host pass on the same matrix (SPMD single-
    # controller-per-process, like the raft-dask fit path), so the
    # callback serves any local index from the full host stack
    leaves = {
        k: jax.make_array_from_callback(
            v.shape, NamedSharding(mesh, P(axis)),
            lambda idx, v=v: v[idx])
        for k, v in stacked.items()}
    return ShardedTiledELL(
        shape=shape, rb=rb, C=C, R=R, E=E,
        n_col_tiles=max(1, -(-n_cols // C)), n_row_tiles=rb // R,
        axis=axis, mesh=mesh, **leaves)


def _shard_map_blocks(S: ShardedTiledELL, per_block_fn, operand):
    """ONE copy of the shard_map plumbing shared by spmv/spmm: rebuild
    the shard-local TiledELL from the stacked leaves and apply
    ``per_block_fn(tiled, operand) -> [1, ...]`` per mesh device; block
    outputs concatenate on the sharded axis."""

    def local(vals, cl, cct, pr, rl, crt, vis, op):
        t = TiledELL(
            shape=(S.rb, S.shape[1]), C=S.C, R=S.R, E=S.E,
            vals=vals[0], col_local=cl[0], chunk_col_tile=cct[0],
            perm=None, perm_rows=pr[0], row_local=rl[0],
            chunk_row_tile=crt[0], visited_row_tiles=vis[0],
            n_col_tiles=S.n_col_tiles, n_row_tiles=S.n_row_tiles)
        return per_block_fn(t, op)

    a = S.axis
    return jax.shard_map(
        local, mesh=S.mesh,
        in_specs=(P(a), P(a), P(a), P(a), P(a), P(a), P(a), P()),
        # check_vma can't see through pallas_call's ShapeDtypeStruct
        # outputs; the body is per-shard-pure so the check adds nothing
        out_specs=P(a), check_vma=False)(
            S.vals, S.col_local, S.chunk_col_tile, S.perm_rows,
            S.row_local, S.chunk_row_tile, S.visited_row_tiles, operand)


@instrument("sparse.spmv_sharded")
def spmv_sharded(S: ShardedTiledELL, x) -> jax.Array:
    """y = A @ x for a :class:`ShardedTiledELL`: each mesh device runs
    the single-device tiled SpMV on its row block (replicated x), and
    the blocks concatenate on the sharded axis. Jittable; composes with
    the jitted Lanczos loop (GSPMD all-gathers y where needed)."""
    from raft_tpu.ops.spmv_pallas import spmv_tiled

    fault_point("spmv_sharded")
    x = jnp.asarray(x, jnp.float32)
    y = _shard_map_blocks(S, lambda t, xr: spmv_tiled(t, xr)[None, :], x)
    return y.reshape(-1)[:S.shape[0]]


@instrument("sparse.spmm_sharded")
def spmm_sharded(S: ShardedTiledELL, B) -> jax.Array:
    """C = A @ B for a :class:`ShardedTiledELL` and dense replicated
    ``B`` [n_cols, kB] — the multi-vector building block (the sparse
    solvers themselves still take single-device operands; wire-up of
    randomized_svds/spmm-based solvers over the mesh goes through
    ``sparse.linalg.spmm`` dispatch). Each shard runs the single-device
    spmm_tiled on its row block; blocks concatenate on the axis."""
    from raft_tpu.ops.spmv_pallas import spmm_tiled

    B = jnp.asarray(B, jnp.float32)
    C = _shard_map_blocks(S, lambda t, Br: spmm_tiled(t, Br)[None], B)
    return C.reshape(-1, B.shape[1])[:S.shape[0]]
