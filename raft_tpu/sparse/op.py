"""Sparse element/row operations.

(ref: cpp/include/raft/sparse/op/ — detail/filter.cuh (276, remove zeros),
op/reduce.cuh (duplicate reduction), op/row_op.cuh, op/slice.cuh (csr row
slice), op/sort.cuh (coo sort).)
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix


def coo_sort(coo: COOMatrix) -> COOMatrix:
    """Sort by (row, col). (ref: op/sort.cuh ``coo_sort``)"""
    order = jnp.lexsort((coo.cols, coo.rows))
    return COOMatrix(coo.rows[order], coo.cols[order], coo.values[order],
                     coo.shape)


def coo_remove_zeros(coo: COOMatrix, eps: float = 0.0) -> COOMatrix:
    """Drop entries with |value| <= eps. Output nnz is data-dependent →
    host step, like the reference's count-then-fill.
    (ref: op/detail/filter.cuh ``coo_remove_zeros``)"""
    vals = np.asarray(coo.values)
    keep = np.abs(vals) > eps
    return COOMatrix(
        jnp.asarray(np.asarray(coo.rows)[keep]),
        jnp.asarray(np.asarray(coo.cols)[keep]),
        jnp.asarray(vals[keep]),
        coo.shape,
    )


def max_duplicates(coo: COOMatrix) -> COOMatrix:
    """Reduce duplicate (row, col) entries keeping the max.
    (ref: op/reduce.cuh ``max_duplicates``)"""
    return _reduce_duplicates(coo, "max")


def sum_duplicates(coo: COOMatrix) -> COOMatrix:
    """(ref: op/reduce.cuh duplicate sum / ``compute_duplicates_mask``)"""
    return _reduce_duplicates(coo, "sum")


def _reduce_duplicates(coo: COOMatrix, how: str) -> COOMatrix:
    r = np.asarray(coo.rows)
    c = np.asarray(coo.cols)
    if how == "sum" and coo.values.dtype == jnp.float32:
        # native host coalesce fast path (cpp/hostops.cpp host_coo_coalesce)
        from raft_tpu import native

        out_r, out_c, out_v = native.host_coo_coalesce(
            r, c, np.asarray(coo.values), coo.shape[1])
        return COOMatrix(jnp.asarray(out_r), jnp.asarray(out_c),
                         jnp.asarray(out_v), coo.shape)
    keys = r.astype(np.int64) * coo.shape[1] + c
    uniq, inverse = np.unique(keys, return_inverse=True)
    seg = jnp.asarray(inverse)
    if how == "max":
        vals = jax.ops.segment_max(coo.values, seg, num_segments=len(uniq))
    else:
        vals = jax.ops.segment_sum(coo.values, seg, num_segments=len(uniq))
    return COOMatrix(
        jnp.asarray((uniq // coo.shape[1]).astype(np.int32)),
        jnp.asarray((uniq % coo.shape[1]).astype(np.int32)),
        vals, coo.shape)


def csr_row_op(csr: CSRMatrix, op: Callable) -> CSRMatrix:
    """Apply ``op(row_id, value) -> value`` to every nonzero.
    (ref: op/row_op.cuh ``csr_row_op`` — per-row lambda over the row's
    span; the functional rendering passes the row id per element.)"""
    return csr.with_values(op(csr.row_ids(), csr.values))


def csr_row_slice(csr: CSRMatrix, start_row: int, stop_row: int) -> CSRMatrix:
    """Rows [start_row, stop_row). (ref: op/slice.cuh
    ``csr_row_slice_indptr`` / ``csr_row_slice_populate``)"""
    expects(0 <= start_row < stop_row <= csr.shape[0], "csr_row_slice: bad range")
    indptr = np.asarray(csr.indptr)
    lo, hi = int(indptr[start_row]), int(indptr[stop_row])
    new_indptr = jnp.asarray(indptr[start_row:stop_row + 1] - lo)
    return CSRMatrix(new_indptr, csr.indices[lo:hi], csr.values[lo:hi],
                     (stop_row - start_row, csr.shape[1]))
