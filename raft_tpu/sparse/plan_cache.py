"""Persistent sparse tile-plan cache.

The tiled-ELL / pair layouts (:mod:`raft_tpu.sparse.tiled`) are one-time
HOST conversions — 62.7 s cold / 39.8 s for the pairs layout at the
R3_SPECTRAL_PROFILE 2M-nnz scale — that previously amortized only
within one process. This module persists prepared plans to disk, keyed
by a SPARSITY-STRUCTURE fingerprint (shape + tiling params + a digest
of the row/col id streams), so a spectral job restarted tomorrow pays a
~ms ``np.load`` instead of a minute of sorting.

Contract:

- The fingerprint covers everything the LAYOUT depends on: layout kind
  + version, matrix shape, (C, R, E), and the exact nnz id streams.
  Two matrices with the same structure share a plan.
- Plans whose arrays bake VALUES in (tiled-ELL ``vals``) also store a
  values digest in the sidecar metadata; a lookup with different values
  is an honest MISS (recompute + overwrite) — never a silently wrong
  hit. The pair layout is structure-only, so it hits regardless of
  values.
- Loads/saves NEVER raise into the conversion path: any I/O or format
  problem degrades to a miss (save: a logged warning). Writes go
  through the shared :mod:`raft_tpu.core.diskio` atomic-write helper
  (tmp + fsync + rename + parent-dir fsync), so a killed process — or
  a power loss right after the rename — cannot leave a torn plan.

Config (env):

- ``RAFT_TPU_TILE_PLAN_CACHE`` — cache directory; ``0``/``off``
  disables; unset defaults to ``~/.cache/raft_tpu/tile_plans``.
- ``RAFT_TPU_TILE_PLAN_CACHE_MIN_NNZ`` — persistence threshold
  (default 200000): tiny conversions are cheaper than the disk round
  trip and would litter the cache (the tier-1 suite's matrices stay
  below it unless a test opts in).
- ``RAFT_TPU_TILE_PLAN_CACHE_MAX_MB`` — total on-disk size cap
  (default 2048 MB; ``0``/negative = unbounded). Enforced after every
  save with least-recently-USED eviction: a hit touches its file's
  mtime, so long-lived structures survive and one-off fingerprints age
  out — without the cap the cache grows without bound across
  processes. Evictions are counted
  (``raft_tpu_tile_plan_cache_evictions_total``).

Hits/misses are counted in the observability registry
(``raft_tpu_tile_plan_cache_{hits,misses}_total``). Reads carry the
``plan_cache_read`` fault-injection site: an injected ``corrupt`` read
degrades to a miss (recompute), exactly like a real torn file.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, Optional, Tuple

import numpy as np

PLAN_VERSION = 1
_DEFAULT_MIN_NNZ = 200_000
_DEFAULT_MAX_MB = 2048

HITS = "raft_tpu_tile_plan_cache_hits_total"
MISSES = "raft_tpu_tile_plan_cache_misses_total"
EVICTIONS = "raft_tpu_tile_plan_cache_evictions_total"


def cache_dir() -> Optional[str]:
    """The active cache directory, or None when disabled."""
    env = os.environ.get("RAFT_TPU_TILE_PLAN_CACHE")
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "false"):
            return None
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu",
                        "tile_plans")


def min_nnz() -> int:
    try:
        return int(os.environ.get("RAFT_TPU_TILE_PLAN_CACHE_MIN_NNZ",
                                  _DEFAULT_MIN_NNZ))
    except ValueError:
        return _DEFAULT_MIN_NNZ


def enabled_for(nnz: int) -> bool:
    return cache_dir() is not None and nnz >= min_nnz()


def max_cache_bytes() -> Optional[int]:
    """Size cap in bytes, or None (unbounded) for a non-positive /
    unparseable ``RAFT_TPU_TILE_PLAN_CACHE_MAX_MB``... 0 disables the
    cap, not the cache."""
    raw = os.environ.get("RAFT_TPU_TILE_PLAN_CACHE_MAX_MB")
    try:
        mb = float(raw) if raw is not None else float(_DEFAULT_MAX_MB)
    except ValueError:
        mb = float(_DEFAULT_MAX_MB)
    if mb <= 0:
        return None
    return int(mb * (1 << 20))


def _digest(*parts) -> str:
    h = hashlib.sha256()
    for part in parts:
        if isinstance(part, np.ndarray):
            a = np.ascontiguousarray(part)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        else:
            h.update(repr(part).encode())
        h.update(b"|")
    return h.hexdigest()[:32]


def structure_fingerprint(kind: str, shape: Tuple[int, int],
                          params: Tuple, rows: np.ndarray,
                          cols: np.ndarray) -> str:
    """Layout-plan key: kind + plan version + shape + tiling params +
    the exact id streams (the CSR indptr/indices decompose into exactly
    these row/col streams — hashing the streams keys both input
    formats identically)."""
    return _digest(kind, PLAN_VERSION, tuple(shape), tuple(params),
                   np.asarray(rows, np.int64), np.asarray(cols, np.int64))


def values_digest(vals) -> str:
    return _digest(np.asarray(vals, np.float32))


def _count(hit: bool) -> None:
    try:
        from raft_tpu.observability import get_registry

        reg = get_registry()
        if not reg.enabled:
            return
        if hit:
            reg.counter(HITS, help="Tile plans served from the "
                                   "persistent cache").inc()
        else:
            reg.counter(MISSES, help="Tile-plan cache lookups that "
                                     "recomputed").inc()
    except Exception:
        pass


def load_plan(fingerprint: str,
              vals_digest: Optional[str] = None) -> Optional[Dict]:
    """The cached plan arrays for ``fingerprint``, or None (miss). When
    ``vals_digest`` is given, a stored plan with a different values
    digest is a miss (the plan's arrays bake those values in). A hit
    touches the file's mtime (the LRU clock for the size cap)."""
    d = cache_dir()
    if d is None:
        return None
    path = os.path.join(d, f"{fingerprint}.npz")
    try:
        from raft_tpu.resilience import fault_point

        if fault_point("plan_cache_read") == "corrupt":
            _count(False)
            return None     # injected torn read → honest miss
    except ImportError:
        pass
    try:
        with np.load(path, allow_pickle=False) as z:
            meta_ver = int(z["__version__"])
            if meta_ver != PLAN_VERSION:
                _count(False)
                return None
            if vals_digest is not None:
                stored = str(z["__vals_digest__"])
                if stored != vals_digest:
                    _count(False)
                    return None
            out = {k: z[k] for k in z.files
                   if not k.startswith("__")}
    except Exception:
        _count(False)
        return None
    _count(True)
    try:
        os.utime(path)          # LRU touch: a hit keeps the plan young
    except OSError:
        pass
    return out


def save_plan(fingerprint: str, arrays: Dict[str, np.ndarray],
              vals_digest: Optional[str] = None) -> bool:
    """Persist a plan atomically; returns False (with a logged warning)
    on any failure — persistence is an optimization, never an error."""
    d = cache_dir()
    if d is None:
        return False
    try:
        os.makedirs(d, exist_ok=True)
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        payload["__version__"] = np.asarray(PLAN_VERSION)
        if vals_digest is not None:
            payload["__vals_digest__"] = np.asarray(vals_digest)
        from raft_tpu.core.diskio import atomic_write

        atomic_write(os.path.join(d, f"{fingerprint}.npz"),
                     lambda f: np.savez(f, **payload))
        _enforce_cap(d)
        return True
    except Exception as e:
        try:
            from raft_tpu.core.logger import log_warn

            log_warn("tile-plan cache: failed to persist %s (%s: %s)",
                     fingerprint, type(e).__name__, e)
        except Exception:
            pass
        return False


def _enforce_cap(d: str) -> int:
    """Evict least-recently-used plans until the directory fits the
    size cap; returns the number evicted. Never raises — a racing
    process deleting a file concurrently is fine."""
    cap = max_cache_bytes()
    if cap is None:
        return 0
    evicted = 0
    try:
        entries = []
        with os.scandir(d) as it:
            for e in it:
                if not e.name.endswith(".npz"):
                    continue
                try:
                    st = e.stat()
                    entries.append((st.st_mtime, st.st_size, e.path))
                except OSError:
                    continue
        total = sum(size for _, size, _ in entries)
        entries.sort()               # oldest mtime (least recently used) first
        for _, size, path in entries:
            if total <= cap:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            evicted += 1
        if evicted:
            try:
                from raft_tpu.observability import get_registry

                reg = get_registry()
                if reg.enabled:
                    reg.counter(EVICTIONS,
                                help="Tile plans evicted by the LRU "
                                     "size cap").inc(evicted)
            except Exception:
                pass
            from raft_tpu.core.logger import log_info

            log_info("tile-plan cache: evicted %d LRU plan(s) to fit "
                     "the %d-byte cap", evicted, cap)
    except Exception:
        pass
    return evicted
