"""Sparse linear algebra.

(ref: cpp/include/raft/sparse/linalg/ — spmm.hpp:42 (cusparse SpMM),
sddmm.hpp:43, masked_matmul.cuh:47,92, detail/add.cuh, degree.cuh,
detail/norm.cuh, normalize, transpose (csr2csc), detail/symmetrize.cuh,
laplacian.cuh:20,32,60,93.)

TPU-first design: there is no cusparse; SpMV/SpMM become gather +
segment-sum (XLA lowers segment_sum to sorted scatter-add, efficient for
static-nnz COO), and SDDMM becomes row-gather + fused dot. Irregular
scatter is the TPU's weak spot (SURVEY hard part (b)) — the Pallas ELL
kernel in raft_tpu.ops.spmv_pallas covers the perf-critical regular case;
these are the general-correctness paths with identical semantics.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import BitmapView, BitsetView
from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.linalg.types import NormType

Sparse = Union[COOMatrix, CSRMatrix]


def _as_coo_parts(A: Sparse):
    if isinstance(A, CSRMatrix):
        return A.row_ids(), A.indices, A.values, A.shape
    return A.rows, A.cols, A.values, A.shape


def spmv(res, A, x) -> jax.Array:
    """y = A @ x. (ref: cusparseSpMV wrappers; the Lanczos hot loop's matvec
    — sparse/solver/detail/lanczos.cuh:263-271.)

    ``A`` may be COO/CSR (gather + segment-sum path) or a pre-tiled
    :class:`raft_tpu.sparse.tiled.TiledELL` (the Pallas lane-select
    kernels in raft_tpu.ops.spmv_pallas — prepare once with
    :func:`prepare_spmv` for repeated matvecs, e.g. Lanczos).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.sparse import CSRMatrix, linalg
    >>> A = CSRMatrix.from_dense(np.array([[1.0, 0.0], [0.0, 2.0]]))
    >>> np.asarray(linalg.spmv(None, A, np.array([3.0, 4.0]))).tolist()
    [3.0, 8.0]
    """
    from raft_tpu.sparse.tiled import TiledELL, TiledPairsSpmv

    from raft_tpu.sparse.sharded import ShardedTiledELL, spmv_sharded

    if isinstance(A, ShardedTiledELL):
        return spmv_sharded(A, x)
    if isinstance(A, TiledPairsSpmv):
        from raft_tpu.ops.spmv_pallas import spmv_pair_tiled

        return spmv_pair_tiled(A, x)
    if isinstance(A, TiledELL):
        from raft_tpu.ops.spmv_pallas import spmv_tiled

        return spmv_tiled(A, x)
    rows, cols, vals, shape = _as_coo_parts(A)
    x = jnp.asarray(x)
    return jax.ops.segment_sum(vals * x[cols], rows, num_segments=shape[0])


def prepare_spmv(A: Sparse, C: int = 512, R: int = 256, E: int = 2048,
                 layout: str = "ell"):
    """One-time conversion of a sparse matrix to a Pallas-SpMV layout;
    the returned operand is accepted by :func:`spmv` and the
    Lanczos/spectral solvers. (ref: the role of cusparse's conversion +
    SpMV-descriptor preparation.)

    ``layout="ell"`` (default) builds the v2 tiled-ELL operand: the
    gather→scatter bridge is an 8-aligned ROW gather (MEASURED at 2M
    nnz on v5e: 5.9 ms vs 51.3 segment-sum and 21.3 for the legacy
    scalar-perm bridge); it also serves :func:`spmm`. ``layout="pairs"``
    builds the single-kernel pair-tiled operand — only a win for
    BLOCK-CLUSTERED structures (each (row-tile, col-tile) bucket pads
    to E slots: a uniformly random 2M-nnz graph measured 157 ms from
    ~67× pad blowup; tile_csr_pairs warns when that happens)."""
    if layout == "pairs":
        from raft_tpu.sparse.tiled import tile_csr_pairs

        return tile_csr_pairs(A, C=C, R=R, E=E)
    if layout != "ell":
        raise ValueError(f"prepare_spmv: layout must be 'pairs' or "
                         f"'ell', got {layout!r}")
    from raft_tpu.sparse.tiled import tile_csr

    return tile_csr(A, C=C, R=R, E=E)


def spmm(res, A, B, alpha=1.0, beta=0.0, C=None) -> jax.Array:
    """C = alpha A @ B + beta C for dense B. (ref: sparse/linalg/spmm.hpp:42)

    ``A`` may be COO/CSR (gather + segment-sum, dtype-preserving) or a
    pre-tiled :class:`TiledELL` (MXU one-hot kernels — see
    ops.spmv_pallas.spmm_tiled). The tiled perf path computes in f32 —
    the kernel/layout dtype — so f64 operands should stay on the
    COO/CSR path (see the README dtype policy)."""
    from raft_tpu.sparse.sharded import ShardedTiledELL, spmm_sharded
    from raft_tpu.sparse.tiled import TiledELL, TiledPairsSpmv

    B = jnp.asarray(B)
    if isinstance(A, TiledPairsSpmv):
        raise TypeError(
            "spmm: got a pair-tiled SpMV operand; prepare with "
            "prepare_spmv(A, layout='ell') for multi-vector products")
    if isinstance(A, ShardedTiledELL):
        out = alpha * spmm_sharded(A, B)   # epilogue shared below
    elif isinstance(A, TiledELL):
        from raft_tpu.ops.spmv_pallas import spmm_tiled

        out = alpha * spmm_tiled(A, B)
    else:
        rows, cols, vals, shape = _as_coo_parts(A)
        out = alpha * jax.ops.segment_sum(vals[:, None] * B[cols, :], rows,
                                          num_segments=shape[0])
    if C is not None and beta != 0.0:
        out = out + beta * jnp.asarray(C)
    return out


def prepare_sddmm(structure: Sparse, R: int = 256, C: int = 512,
                  E: int = 2048):
    """One-time conversion of a sparsity structure to the pair-tiled
    layout used by the blocked SDDMM kernel; the returned operand is
    accepted by :func:`sddmm` (as ``structure``) and
    :func:`masked_matmul` (as ``prepared``) for repeated sampled
    products over the same pattern. (ref: the cusparse SDDMM
    descriptor-preparation role.)"""
    from raft_tpu.sparse.tiled import tile_pairs

    return tile_pairs(structure, R=R, C=C, E=E)


def sddmm(res, A, B, structure, alpha=1.0, beta=0.0) -> Sparse:
    """Sampled dense-dense matmul: C_ij = alpha·(A @ B)_ij + beta·C_ij at the
    nonzero positions of ``structure`` only. A is [m×k], B is [k×n].
    (ref: sparse/linalg/sddmm.hpp:43) Returns a sparse matrix sharing
    structure's sparsity pattern.

    ``structure`` may be COO/CSR (gather path, dtype-preserving) or a
    pre-tiled :class:`raft_tpu.sparse.tiled.TiledPairs` (the blocked MXU
    kernel — ops.sddmm_pallas; f32, the per-block dense tile never
    leaves VMEM). The tiled path has no values, so beta must be 0; the
    result is a COO matrix in the structure's original entry order."""
    from raft_tpu.sparse.tiled import TiledPairs

    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if isinstance(structure, TiledPairs):
        from raft_tpu.ops.sddmm_pallas import sddmm_tiled

        expects(beta == 0.0, "sddmm: TiledPairs carries no values "
                "(beta must be 0)")
        vals = alpha * sddmm_tiled(structure, A, B)
        return COOMatrix(structure.rows, structure.cols, vals,
                         structure.shape)
    rows, cols, vals, shape = _as_coo_parts(structure)
    expects(A.shape[0] == shape[0] and B.shape[1] == shape[1],
            "sddmm: shape mismatch")
    prod = jnp.sum(A[rows, :] * B[:, cols].T, axis=1)
    new_vals = alpha * prod + (beta * vals if beta != 0.0 else 0.0)
    return structure.with_values(new_vals.astype(vals.dtype))


def masked_matmul(res, A, B, mask: "BitmapView | BitsetView", alpha=1.0,
                  beta=0.0, prepared=None) -> Sparse:
    """C = alpha·(A @ Bᵀ) ∘ mask, result sparse.
    (ref: sparse/linalg/masked_matmul.cuh:47,92 — bitmap/bitset-masked
    dense×dense → sparse via SDDMM; note the reference contracts A [m×k]
    with B [n×k] transposed.)

    For repeated products over the SAME mask, pass ``prepared`` — the
    :func:`prepare_sddmm` layout of the mask's structure — to route
    through the blocked MXU kernel instead of re-deriving the CSR
    structure per call (requires beta == 0)."""
    from raft_tpu.sparse.convert import bitmap_to_csr, bitset_to_csr

    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if prepared is not None:
        return sddmm(res, A, B.T, prepared, alpha=alpha, beta=beta)
    if isinstance(mask, BitmapView):
        structure = bitmap_to_csr(mask)
    else:
        structure = bitset_to_csr(mask, n_repeat=A.shape[0])
    return sddmm(res, A, B.T, structure, alpha=alpha, beta=beta)


def add(res, A: Sparse, B: Sparse, dedup: bool = False) -> CSRMatrix:
    """Sparse + sparse with structure union.
    (ref: sparse/linalg/add.cuh — csr_add_calc_inds/csr_add_finalize two-
    phase; here the union structure is discovered on host once, then values
    combine on device.)

    ``dedup=True`` prunes duplicate slots to the reference's canonical
    structural nnz (one host sync — see _coalesce_to_csr)."""
    ra, ca, va, shape_a = _as_coo_parts(A)
    rb, cb, vb, shape_b = _as_coo_parts(B)
    expects(shape_a == shape_b, "sparse add: shape mismatch")
    rows = jnp.concatenate([ra, rb])
    cols = jnp.concatenate([ca, cb])
    vals = jnp.concatenate([va, vb])
    return _coalesce_to_csr(rows, cols, vals, shape_a, dedup=dedup)


def _coalesce_to_csr(rows, cols, vals, shape, dedup: bool = False
                     ) -> CSRMatrix:
    """Sum duplicate (row, col) entries → sorted CSR, ON DEVICE with
    static shapes (duplicate slots become explicit zeros — see
    _device_coalesce_sorted for the exact contract; value semantics are
    identical to an exact dedup, structural nnz keeps the slots). The
    exact-dedup host coalesce remains available as the public
    ``op.sum_duplicates``.

    ``dedup=True`` prunes the duplicate slots afterwards — canonical
    structural nnz like the reference, at the cost of ONE host sync for
    the kept count (vs zero syncs for the default)."""
    from raft_tpu.sparse.convert import sorted_coo_to_csr

    r, c, v, keep = _device_coalesce_sorted(rows, cols, vals)
    if dedup and r.shape[0]:
        n_kept = int(jnp.sum(keep))          # the one host sync
        idx = jnp.nonzero(keep, size=n_kept)[0]
        r, c, v = r[idx], c[idx], v[idx]
    return sorted_coo_to_csr(COOMatrix(r, c, v, shape))


def degree(res, A: Sparse) -> jax.Array:
    """Per-row nonzero count. (ref: sparse/linalg/degree.cuh ``coo_degree``)"""
    rows, _, _, shape = _as_coo_parts(A)
    return jnp.bincount(rows, length=shape[0]).astype(jnp.int32)


def row_norm(res, A: Sparse, norm_type: NormType = NormType.L2) -> jax.Array:
    """Per-row norms of the values. (ref: sparse/linalg/detail/norm.cuh —
    row L1/L2; L2 here returns the sum of squares like the dense row_norm.)"""
    rows, _, vals, shape = _as_coo_parts(A)
    if norm_type == NormType.L1:
        contrib = jnp.abs(vals)
        return jax.ops.segment_sum(contrib, rows, num_segments=shape[0])
    if norm_type == NormType.L2:
        return jax.ops.segment_sum(vals * vals, rows, num_segments=shape[0])
    return jax.ops.segment_max(jnp.abs(vals), rows, num_segments=shape[0])


def row_normalize(res, A: Sparse, norm_type: NormType = NormType.L1) -> Sparse:
    """Scale each row to unit norm. (ref: sparse/linalg/normalize.cuh)"""
    rows, _, vals, shape = _as_coo_parts(A)
    norms = row_norm(res, A, norm_type)
    if norm_type == NormType.L2:
        norms = jnp.sqrt(norms)
    per_val = norms[rows]
    safe = jnp.where(per_val == 0, jnp.ones_like(per_val), per_val)
    return A.with_values(jnp.where(per_val == 0, jnp.zeros_like(vals), vals / safe))


def transpose(res, A: CSRMatrix) -> CSRMatrix:
    """CSR transpose (csr2csc). (ref: sparse/linalg/transpose.cuh)"""
    from raft_tpu.sparse.convert import coo_to_csr

    rows, cols, vals, shape = _as_coo_parts(A)
    return coo_to_csr(COOMatrix(cols, rows, vals, (shape[1], shape[0])))


def symmetrize(res, A: Sparse, dedup: bool = False) -> CSRMatrix:
    """Return A + Aᵀ on the union structure.
    (ref: sparse/linalg/detail/symmetrize.cuh COO symmetrization)

    ``dedup=True`` prunes duplicate slots to canonical structural nnz
    (one host sync — see _coalesce_to_csr)."""
    rows, cols, vals, shape = _as_coo_parts(A)
    expects(shape[0] == shape[1], "symmetrize: square input required")
    r2 = jnp.concatenate([rows, cols])
    c2 = jnp.concatenate([cols, rows])
    v2 = jnp.concatenate([vals, vals])
    return _coalesce_to_csr(r2, c2, v2, shape, dedup=dedup)


@jax.jit
def _device_coalesce_sorted(rows, cols, vals):
    """Device-side coalesce with STATIC shapes: sort by (row, col), sum
    each duplicate run into its first slot, zero the rest. Output nnz
    equals input nnz — duplicate slots become EXPLICIT ZEROS, which is
    value-exact for every summing consumer (to_dense, SpMV/SpMM folds,
    value norms, the tiled-layout conversion) but inflates STRUCTURAL
    counts (``nnz``, ``degree()``'s bincount) by the duplicate slots.
    Exists because the exact host coalesce round-trips the arrays
    through the host (MEASURED: 1.85 s of config 4's 4.8 s at 2M nnz
    was this one transfer+sort); this runs in ~tens of ms on device.

    Also returns the run-first mask (True at the slot an exact dedup
    keeps) so dedup callers don't recompute it."""
    if vals.shape[0] == 0:
        return rows, cols, vals, jnp.ones((0,), bool)
    order = jnp.lexsort((cols, rows))
    r, c, v = rows[order], cols[order], vals[order]
    first = jnp.concatenate([
        jnp.ones((1,), bool),
        (r[1:] != r[:-1]) | (c[1:] != c[:-1])])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    sums = jax.ops.segment_sum(v, seg, num_segments=v.shape[0])
    v_out = jnp.where(first, sums[seg], jnp.zeros_like(v))
    return r, c, v_out, first


def compute_graph_laplacian(res, A: Sparse, dedup: bool = False
                            ) -> CSRMatrix:
    """L = D − A (out-degree Laplacian; diagonal of A ignored, one diagonal
    entry added per row — ref: sparse/linalg/laplacian.cuh:20,32 and the
    kernel in detail/laplacian.cuh: input diagonal treated as zero).

    Duplicate (row, col) entries are coalesced ON DEVICE into explicit
    zeros (static shapes — see _device_coalesce_sorted), so ``L.nnz``
    (and ``degree`` — a structural count) include the input's duplicate
    slots; VALUES are exact under summation (``to_dense`` identical).
    ``dedup=True`` opts into the reference's canonical structural nnz
    at the cost of one host sync (see _coalesce_to_csr)."""
    rows, cols, vals, shape = _as_coo_parts(A)
    expects(shape[0] == shape[1],
            "The graph Laplacian can only be computed on a square adjacency matrix")
    off_diag = rows != cols
    masked_vals = jnp.where(off_diag, vals, jnp.zeros_like(vals))
    deg = jax.ops.segment_sum(masked_vals, rows, num_segments=shape[0])
    # union of -A's off-diagonal entries and the degree diagonal
    n = shape[0]
    diag_idx = jnp.arange(n, dtype=rows.dtype)
    all_rows = jnp.concatenate([rows, diag_idx])
    all_cols = jnp.concatenate([cols, diag_idx])
    all_vals = jnp.concatenate([-masked_vals, deg])
    return _coalesce_to_csr(all_rows, all_cols, all_vals, shape,
                            dedup=dedup)


def laplacian_normalized(res, A: Sparse) -> Tuple[CSRMatrix, jax.Array]:
    """Normalized Laplacian D^(−1/2) L D^(−1/2); also returns the scaled
    diagonal D^(−1/2) (zero degrees mapped to 1 before the inverse sqrt,
    matching the reference's zero_to_one functor).
    (ref: sparse/linalg/laplacian.cuh:60,93)"""
    L = compute_graph_laplacian(res, A)
    diag = diagonal(res, L)  # degree vector
    safe = jnp.where(diag == 0, jnp.ones_like(diag), diag)
    d_inv_sqrt = 1.0 / jnp.sqrt(safe)
    rows, cols, vals, shape = _as_coo_parts(L)
    scaled = vals * d_inv_sqrt[rows] * d_inv_sqrt[cols]
    return L.with_values(scaled), d_inv_sqrt


def diagonal(res, A: Sparse) -> jax.Array:
    """Extract the main diagonal (the one implementation; sparse.matrix
    re-exports it). (ref: sparse/matrix/detail/diagonal.cuh)"""
    rows, cols, vals, shape = _as_coo_parts(A)
    on_diag = rows == cols
    return jax.ops.segment_sum(jnp.where(on_diag, vals, jnp.zeros_like(vals)),
                               rows, num_segments=shape[0])
