"""raft_tpu.sparse.solver — Lanczos, randomized SVD, MST. (ref:
cpp/include/raft/sparse/solver, SURVEY §2.5.)"""

from raft_tpu.sparse.solver.lanczos import lanczos_compute_eigenpairs
from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig
from raft_tpu.sparse.solver.cholesky_qr import cholesky_qr, cholesky_qr2
from raft_tpu.sparse.solver.randomized_svds import (
    SvdsConfig,
    randomized_svds,
    sign_correction,
)
from raft_tpu.sparse.solver.mst import GraphCOO, MSTResult, mst

__all__ = [
    "lanczos_compute_eigenpairs", "LANCZOS_WHICH", "LanczosSolverConfig",
    "cholesky_qr", "cholesky_qr2",
    "SvdsConfig", "randomized_svds", "sign_correction",
    "GraphCOO", "MSTResult", "mst",
]
