"""Thick-restart Lanczos eigensolver.

(ref: cpp/include/raft/sparse/solver/lanczos.cuh:35,60,87 public API (COO +
CSR overloads); impl sparse/solver/detail/lanczos.cuh (799 LoC):
``lanczos_smallest:402`` host-orchestrated thick-restart loop,
``lanczos_aux:248`` Krylov tridiagonalization (cusparse SpMV + cublas
orthogonalization), ``lanczos_solve_ritz:129`` small tridiagonal eig via
eigDC. Runtime entry: cpp/src/raft_runtime/solver/lanczos_solver.cuh:11;
python binding python/pylibraft/pylibraft/sparse/linalg/lanczos.pyx:100.)

TPU re-design: the Krylov build keeps the whole (ncv+1)×n basis resident in
HBM and does FULL re-orthogonalization as two dense [ncv+1,n]×[n] matmuls
per step — MXU work replacing the reference's sequence of dot/axpy cublas
calls (a better hardware fit: one big contraction instead of j small ones,
and unconditionally stable, so the projected matrix is computed as full
Rayleigh-Ritz rather than strict tridiagonal). Masked rows make every step
static-shape, so one restart cycle is a single jitted program
(``lax.fori_loop`` over steps, ``eigh`` on the ncv×ncv projection inside).
The restart loop runs on host with an ``interruptible`` cancellation point
per cycle, exactly like the reference's host hot loop (SURVEY §3.1).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core import interruptible, nvtx
from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig

Operand = Union[COOMatrix, CSRMatrix, "TiledELL", "TiledPairsSpmv",
                jax.Array]


def _matvec(A, x):
    from raft_tpu.sparse.sharded import ShardedTiledELL
    from raft_tpu.sparse.tiled import TiledELL, TiledPairsSpmv

    if isinstance(A, (COOMatrix, CSRMatrix, TiledELL, TiledPairsSpmv,
                      ShardedTiledELL)):
        from raft_tpu.sparse.linalg import spmv

        return spmv(None, A, x)
    return A @ x


def _restart_cycle_impl(A, V, T0, j0, ncv: int):
    """Build Krylov columns j0..ncv-1 with two-pass full
    reorthogonalization, then Rayleigh-Ritz. Returns
    (theta, S, V, beta_last) — V[ncv] is the normalized residual vector."""
    dtype = V.dtype

    def step(j, carry):
        V, T, _ = carry
        row_mask = (jnp.arange(ncv + 1) <= j)[:, None].astype(dtype)
        Vm = V * row_mask
        w = _matvec(A, V[j])
        h = Vm @ w
        w = w - Vm.T @ h
        h2 = Vm @ w            # second Gram-Schmidt pass (stability)
        w = w - Vm.T @ h2
        h = h + h2
        beta = jnp.linalg.norm(w)
        safe_beta = jnp.where(beta > 0, beta, jnp.asarray(1.0, dtype))
        T = T.at[:, j].set(h[:ncv])
        T = T.at[j, :].set(h[:ncv])
        V = V.at[j + 1].set(w / safe_beta)
        T = jnp.where(j + 1 < ncv,
                      T.at[j + 1, j].set(beta).at[j, j + 1].set(beta), T)
        return V, T, beta

    V, T, beta_last = jax.lax.fori_loop(
        j0, ncv, step, (V, T0, jnp.asarray(0.0, dtype)))
    theta, S = jnp.linalg.eigh((T + T.T) / 2)
    return theta, S, V, beta_last


_restart_cycle = jax.jit(_restart_cycle_impl, static_argnames=("ncv",))


def _select(theta, which: LANCZOS_WHICH, k: int):
    """Indices (ascending positions) of the k wanted ritz values."""
    if which == LANCZOS_WHICH.SA:
        idx = jnp.arange(k)
    elif which == LANCZOS_WHICH.LA:
        idx = jnp.arange(theta.shape[0] - k, theta.shape[0])
    elif which == LANCZOS_WHICH.LM:
        idx = jnp.sort(jnp.argsort(-jnp.abs(theta))[:k])
    else:  # SM
        idx = jnp.sort(jnp.argsort(jnp.abs(theta))[:k])
    return idx


def _restart_select(theta, which: LANCZOS_WHICH, k: int, ncv: int):
    """(indices to KEEP across a thick restart, their static count).

    For the extremal modes the restart keeps exactly the k wanted ritz
    vectors. ``SM`` additionally keeps an EXTREMAL DEFLATION BUFFER of
    the largest-magnitude ritz vectors: restarting with only interior
    approximations discards the converged extremal structure the
    interior convergence depends on — measured on the tier-1 fixture
    (n=60, k=4, ncv=25) the unbuffered restart stalls at relative
    residual ~3e-1 with a spurious eigenvalue, while the buffered one
    converges to 8e-7 in fewer steps. The two index sets are disjoint
    by construction (k smallest-|θ| vs nb largest-|θ| with
    k + nb ≤ ncv), so the count is static — jit-safe."""
    if which != LANCZOS_WHICH.SM:
        return _select(theta, which, k), k
    nb = max(0, min(2 * k + 4, ncv - k - 2))
    sm = jnp.argsort(jnp.abs(theta))[:k]
    lm = jnp.argsort(-jnp.abs(theta))[:nb]
    return jnp.sort(jnp.concatenate([sm, lm])), k + nb


def _residual_estimate(theta, S, beta_last, idx, ncv: int):
    """Ritz residual bound |β·S[m−1,i]| + spectrum scale (shared by both
    solve paths)."""
    resid = jnp.abs(beta_last * S[ncv - 1, idx])
    scale = jnp.maximum(jnp.max(jnp.abs(theta)), 1e-30)
    return resid, scale


def _restart_state(theta, S, V, idx, k: int, ncv: int):
    """Thick restart: wanted ritz vectors + residual direction, projected
    T (shared by both solve paths)."""
    ritz = S[:, idx].T @ V[:ncv]
    V2 = jnp.zeros_like(V).at[:k].set(ritz).at[k].set(V[ncv])
    T0 = jnp.zeros((ncv, ncv), V.dtype).at[
        jnp.arange(k), jnp.arange(k)].set(theta[idx])
    return V2, T0


def _extract_eigvecs(S, V, idx, ncv: int):
    """Final ritz-vector extraction (shared by both solve paths)."""
    eigvecs = (S[:, idx].T @ V[:ncv]).T
    return eigvecs / jnp.linalg.norm(eigvecs, axis=0, keepdims=True)


@partial(jax.jit, static_argnames=("ncv", "k", "which"))
def _solve_jitted(A, V0, tol, max_steps, ncv: int, k: int,
                  which: LANCZOS_WHICH):
    """The whole thick-restart loop as ONE compiled program
    (``lax.while_loop`` over cycles) — no per-cycle host dispatch.
    Returns (vals, vecs, max_relative_residual) so the caller can warn on
    non-convergence. tol/max_steps are traced operands: changing them does
    not recompile."""
    dtype = V0.dtype
    theta, S, V, beta_last = _restart_cycle_impl(
        A, V0, jnp.zeros((ncv, ncv), dtype), jnp.asarray(0, jnp.int32), ncv)

    def _rel_resid(theta, S, beta_last):
        idx = _select(theta, which, k)
        resid, scale = _residual_estimate(theta, S, beta_last, idx, ncv)
        return jnp.max(resid) / scale

    def cond(state):
        theta, S, V, beta_last, steps = state
        return (_rel_resid(theta, S, beta_last) > tol) & (steps < max_steps)

    def body(state):
        theta, S, V, beta_last, steps = state
        ridx, k_r = _restart_select(theta, which, k, ncv)
        V2, T0 = _restart_state(theta, S, V, ridx, k_r, ncv)
        theta, S, V, beta_last = _restart_cycle_impl(
            A, V2, T0, jnp.asarray(k_r, jnp.int32), ncv)
        return theta, S, V, beta_last, steps + (ncv - k_r)

    theta, S, V, beta_last, _ = jax.lax.while_loop(
        cond, body, (theta, S, V, beta_last, jnp.asarray(ncv, jnp.int32)))
    idx = _select(theta, which, k)
    eigvecs = _extract_eigvecs(S, V, idx, ncv)
    return theta[idx], eigvecs, _rel_resid(theta, S, beta_last)


def lanczos_compute_eigenpairs(
    res,
    A: Operand,
    config: LanczosSolverConfig,
    v0=None,
) -> Tuple[jax.Array, jax.Array]:
    """Compute ``config.n_components`` eigenpairs of symmetric A.

    Returns (eigenvalues [k] ascending, eigenvectors [n, k]).
    (ref: sparse/solver/lanczos.cuh:35 — the COO/CSR overloads collapse
    into the Operand union here; dense operands are accepted too, which is
    what the BASELINE "Lanczos on 100k×1k dense" config exercises.)
    """
    res = ensure_resources(res)
    k = config.n_components
    from raft_tpu.sparse.sharded import ShardedTiledELL
    from raft_tpu.sparse.tiled import TiledELL, TiledPairsSpmv

    if isinstance(A, (COOMatrix, CSRMatrix)):
        n = A.shape[0]
        dtype = A.values.dtype
    elif isinstance(A, (TiledELL, TiledPairsSpmv, ShardedTiledELL)):
        n = A.shape[0]
        dtype = A.vals.dtype
    else:
        A = jnp.asarray(A)
        n = A.shape[0]
        dtype = A.dtype
    expects(0 < k < n, "lanczos: need 0 < n_components < n")
    ncv = config.ncv if config.ncv is not None else min(n, max(2 * k + 1, 20))
    ncv = min(max(ncv, k + 2), n)

    key = jax.random.key(config.seed)
    if v0 is None:
        key, sub = jax.random.split(key)
        v0 = jax.random.normal(sub, (n,), dtype)
    v0 = jnp.asarray(v0, dtype)
    V = jnp.zeros((ncv + 1, n), dtype)
    V = V.at[0].set(v0 / jnp.linalg.norm(v0))
    T0 = jnp.zeros((ncv, ncv), dtype)

    jit_loop = config.jit_loop
    if jit_loop is None:
        # AUTO: one compiled program on accelerators (per-cycle host
        # round-trips measured 28 s vs 0.6 s for the same 1M-edge solve
        # on the tunneled v5e); the host loop — cancellation points +
        # stagnation early-exit — stays the CPU default
        jit_loop = jax.default_backend() != "cpu"
    if jit_loop:
        with nvtx.annotate("lanczos_compute_eigenpairs[jit]"):
            vals, vecs, rel_resid = _solve_jitted(
                A, V, jnp.asarray(config.tolerance, dtype),
                jnp.asarray(config.max_iterations, jnp.int32),
                ncv, k, config.which)
        rr = float(rel_resid)
        if rr > config.tolerance:
            from raft_tpu.core.logger import log_warn

            log_warn("lanczos[jit]: stopped with relative residual %.3e > "
                     "tolerance %.3e (max_iterations=%d)", rr,
                     config.tolerance, config.max_iterations)
        return vals, vecs

    j0 = 0
    n_steps = 0
    best_resid = None
    stagnant = 0
    with nvtx.annotate("lanczos_compute_eigenpairs"):
        while True:
            interruptible.yield_()  # cancellation point per restart cycle
            theta, S, V, beta_last = _restart_cycle(
                A, V, T0, jnp.asarray(j0, jnp.int32), ncv)
            n_steps += ncv - j0
            idx = _select(theta, config.which, k)
            resid, scale = _residual_estimate(theta, S, beta_last, idx, ncv)
            max_resid = float(jnp.max(resid))
            if bool(jnp.all(resid <= config.tolerance * scale)):
                break
            if n_steps >= config.max_iterations:
                from raft_tpu.core.logger import log_warn

                log_warn("lanczos: max_iterations=%d reached with relative "
                         "residual %.3e > tolerance %.3e",
                         config.max_iterations, max_resid / float(scale),
                         config.tolerance)
                break
            # stop on TRUE flatline only: 50 cycles without even 0.1%
            # improvement means the fp32 floor was hit (e.g. a large zero
            # eigenvalue cluster); legitimately slow geometric convergence
            # (say 0.995×/cycle) still counts as progress and keeps going
            # up to max_iterations
            if best_resid is None or max_resid < 0.999 * best_resid:
                best_resid = max_resid if best_resid is None else min(
                    best_resid, max_resid)
                stagnant = 0
            else:
                stagnant += 1
                if stagnant >= 50:
                    from raft_tpu.core.logger import log_warn

                    log_warn("lanczos: residual stagnated at %.3e (relative "
                             "%.3e > tolerance %.3e) — fp32 floor reached, "
                             "returning best available eigenpairs",
                             max_resid, max_resid / float(scale),
                             config.tolerance)
                    break
            ridx, k_r = _restart_select(theta, config.which, k, ncv)
            V, T0 = _restart_state(theta, S, V, ridx, k_r, ncv)
            j0 = k_r

    return theta[idx], _extract_eigvecs(S, V, idx, ncv)
