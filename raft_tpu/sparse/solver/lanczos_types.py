"""Lanczos solver configuration.

(ref: cpp/include/raft/sparse/solver/lanczos_types.hpp:20
``LANCZOS_WHICH::{LA,LM,SA,SM}`` and :40 ``lanczos_solver_config
{n_components, max_iterations, ncv, tolerance, which, seed}``.)
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class LANCZOS_WHICH(enum.Enum):
    """(ref: lanczos_types.hpp:20)"""

    LA = "LA"  # largest algebraic
    LM = "LM"  # largest magnitude
    SA = "SA"  # smallest algebraic
    SM = "SM"  # smallest magnitude


@dataclasses.dataclass
class LanczosSolverConfig:
    """(ref: lanczos_types.hpp:40 ``lanczos_solver_config``)"""

    n_components: int
    max_iterations: int = 1000
    ncv: Optional[int] = None
    tolerance: float = 1e-6
    which: LANCZOS_WHICH = LANCZOS_WHICH.SA
    seed: int = 42
