"""Lanczos solver configuration.

(ref: cpp/include/raft/sparse/solver/lanczos_types.hpp:20
``LANCZOS_WHICH::{LA,LM,SA,SM}`` and :40 ``lanczos_solver_config
{n_components, max_iterations, ncv, tolerance, which, seed}``.)
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class LANCZOS_WHICH(enum.Enum):
    """(ref: lanczos_types.hpp:20)

    Note on SM: like the reference, SM selects smallest-magnitude ritz
    values from the same Krylov process — WITHOUT shift-invert. Interior
    eigenvalues converge slowly (or stall) for ill-conditioned spectra;
    extremal modes (SA/LA/LM) are the well-conditioned ones.
    """

    LA = "LA"  # largest algebraic
    LM = "LM"  # largest magnitude
    SA = "SA"  # smallest algebraic
    SM = "SM"  # smallest magnitude


@dataclasses.dataclass
class LanczosSolverConfig:
    """(ref: lanczos_types.hpp:40 ``lanczos_solver_config``)

    ``jit_loop=None`` (default) compiles the loop on accelerator
    backends and keeps the host loop on CPU (per-cycle host dispatch
    measured 28 s vs 0.6 s for the same solve on the tunneled v5e);
    ``jit_loop=True`` compiles the whole thick-restart loop into ONE
    program (``lax.while_loop`` over cycles) — no per-cycle host dispatch,
    the right mode for remote/tunneled devices — at the cost of host-side
    cancellation points and the stagnation heuristic (bounded by
    max_iterations instead).
    """

    n_components: int
    max_iterations: int = 1000
    ncv: Optional[int] = None
    tolerance: float = 1e-6
    which: LANCZOS_WHICH = LANCZOS_WHICH.SA
    seed: int = 42
    jit_loop: Optional[bool] = None
