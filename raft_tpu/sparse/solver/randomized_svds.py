"""Randomized SVD for sparse matrices (added to raft in 26.06).

(ref: cpp/include/raft/sparse/solver/randomized_svds.cuh public API with
config sparse/solver/svds_config.hpp; impl detail/randomized_svds.cuh
(241 LoC): Gaussian sketch → cholesky_qr2 (detail/cholesky_qr.cuh) → power
iterations (:135-151) → small SVD; sign correction in
detail/svds_sign_correction.cuh. Runtime entry ``randomized_svds`` in
cpp/src/raft_runtime; python binding
python/pylibraft/pylibraft/sparse/linalg/svds.pyx:73.)
"""

from __future__ import annotations

import dataclasses
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.linalg import spmm, transpose as sp_transpose
from raft_tpu.sparse.solver.cholesky_qr import cholesky_qr2

Sparse = Union[COOMatrix, CSRMatrix]


@dataclasses.dataclass
class SvdsConfig:
    """(ref: sparse/solver/svds_config.hpp)"""

    n_components: int
    n_oversamples: int = 10
    n_power_iters: int = 2
    seed: int = 42


def sign_correction(U, V):
    """Deterministic sign convention: make the largest-|.| entry of each
    left singular vector positive. (ref: detail/svds_sign_correction.cuh)"""
    pivot = jnp.take_along_axis(U, jnp.argmax(jnp.abs(U), axis=0)[None, :], axis=0)
    signs = jnp.sign(jnp.where(pivot == 0, jnp.ones_like(pivot), pivot))
    return U * signs, V * signs


def randomized_svds(res, A: Sparse, config: SvdsConfig, At=None
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Truncated SVD of a sparse matrix. Returns (U [m,k], S [k], V [n,k]).
    (ref: sparse/solver/randomized_svds.cuh ``randomized_svds``)

    MNMG: ``A`` may be a :class:`~raft_tpu.sparse.sharded.ShardedTiledELL`
    — then ``At`` must be the transposed matrix's sharded operand
    (``shard_spmv_operand(transpose(A), mesh)``; a sharded layout has no
    cheap transpose). Every product runs the shard_map SpMM."""
    from raft_tpu.sparse.sharded import ShardedTiledELL

    res = ensure_resources(res)
    k = config.n_components
    m, n = A.shape
    expects(0 < k <= min(m, n), "randomized_svds: bad n_components")
    ell = min(k + config.n_oversamples, min(m, n))
    if isinstance(A, ShardedTiledELL):
        expects(At is not None,
                "randomized_svds: a sharded operand needs At "
                "(shard_spmv_operand of the transposed matrix)")
        expects(isinstance(At, ShardedTiledELL)
                and At.shape == (n, m),
                "randomized_svds: At must be the [n, m] sharded "
                "transpose operand")
        dtype = A.vals.dtype
    else:
        dtype = A.values.dtype
        if isinstance(A, COOMatrix):
            from raft_tpu.sparse.convert import coo_to_csr

            A = coo_to_csr(A)
        if At is None:
            At = sp_transpose(res, A)
        else:
            # same contract the sharded branch enforces — a wrong-shaped
            # At would feed clamped gathers and return silent garbage
            expects(At.shape == (n, m),
                    "randomized_svds: At must be [n, m], got %r",
                    At.shape)

    key = jax.random.key(config.seed)
    omega = jax.random.normal(key, (n, ell), dtype)
    Y = spmm(res, A, omega)                    # m × ell
    Q, _ = cholesky_qr2(Y)
    for _ in range(config.n_power_iters):      # subspace iteration
        Z, _ = cholesky_qr2(spmm(res, At, Q))  # n × ell
        Q, _ = cholesky_qr2(spmm(res, A, Z))   # m × ell
    B = spmm(res, At, Q).T                     # ell × n  (= Qᵀ A)
    Ub, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = (Q @ Ub)[:, :k]
    V = Vt.T[:, :k]
    U, V = sign_correction(U, V)
    return U, S[:k], V
