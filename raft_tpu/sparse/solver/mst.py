"""Minimum spanning tree / forest (Borůvka).

(ref: cpp/include/raft/sparse/solver/mst.cuh:38 ``mst()`` returning
``Graph_COO``, class ``MST_solver`` (mst_solver.cuh:32); kernels
detail/mst_kernels.cuh (324) + detail/mst_solver_inl.cuh (406) — a
Borůvka formulation: per-component min outgoing edge, union, repeat. Used
by downstream single-linkage clustering.)

TPU re-design: each Borůvka round is fully vectorized — a lexicographic
sort ranks every edge within its source component (the same
sort-then-segment trick as sparse select_k), min-label propagation with
pointer jumping replaces the union-find kernels. The reference perturbs
weights to break ties; here ties break deterministically by edge index via
the stable sort. O(log n) host rounds.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix


class GraphCOO(NamedTuple):
    """(ref: solver/mst_solver.cuh ``Graph_COO``)"""

    src: jnp.ndarray
    dst: jnp.ndarray
    weights: jnp.ndarray
    n_edges: int


class MSTResult(NamedTuple):
    mst: GraphCOO
    color: jnp.ndarray  # final component label per vertex


def _min_outgoing(color, src, dst, w):
    """Per-component minimum-weight outgoing edge. Ties break on the
    UNDIRECTED key (min(u,v), max(u,v)) so both endpoint components rank the
    same physical edge identically — a directed-index tie-break would let
    equal-weight edges form ≥3-component cycles. Returns per component:
    chosen edge index or -1."""
    csrc = color[src]
    cdst = color[dst]
    outgoing = csrc != cdst
    # push non-outgoing edges to the end of each group with +inf weight
    wk = jnp.where(outgoing, w, jnp.inf)
    u_lo = jnp.minimum(src, dst)
    u_hi = jnp.maximum(src, dst)
    order = jnp.lexsort((u_hi, u_lo, wk, csrc))
    s_comp = csrc[order]
    # first position of each component in the sorted order wins
    first = jnp.concatenate([jnp.ones((1,), bool),
                             s_comp[1:] != s_comp[:-1]])
    winner_edges = jnp.where(first, order, -1)
    winner_comps = jnp.where(first, s_comp, -1)
    valid = first & jnp.isfinite(wk[order])
    return jnp.where(valid, winner_edges, -1), jnp.where(valid, winner_comps, -1)


def mst(res, G: Union[COOMatrix, CSRMatrix], initial_colors=None) -> MSTResult:
    """Compute the MST/forest of a symmetric weighted graph.
    (ref: sparse/solver/mst.cuh:38 ``mst``; ``initial_colors`` supports the
    downstream connect-components use where a partial forest exists.)"""
    if isinstance(G, CSRMatrix):
        src, dst, w = G.row_ids(), G.indices, G.values
    else:
        src, dst, w = G.rows, G.cols, G.values
    n = G.shape[0]
    expects(G.shape[0] == G.shape[1], "mst: square adjacency required")
    color = (jnp.arange(n, dtype=jnp.int32) if initial_colors is None
             else jnp.asarray(initial_colors, jnp.int32))

    picked_src, picked_dst, picked_w = [], [], []
    max_rounds = int(np.ceil(np.log2(max(n, 2)))) + 1
    for _ in range(max_rounds):
        winner_edges, winner_comps = _min_outgoing(color, src, dst, w)
        edge_ids = np.asarray(winner_edges)
        edge_ids = edge_ids[edge_ids >= 0]
        if edge_ids.size == 0:
            break
        e_src = np.asarray(src)[edge_ids]
        e_dst = np.asarray(dst)[edge_ids]
        e_w = np.asarray(w)[edge_ids]
        col = np.asarray(color)
        cu, cv = col[e_src], col[e_dst]
        # dedupe mutual picks (c1→c2 and c2→c1 choosing the same edge)
        pair_key = np.minimum(cu, cv).astype(np.int64) * n + np.maximum(cu, cv)
        _, keep_idx = np.unique(pair_key, return_index=True)
        e_src, e_dst, e_w = e_src[keep_idx], e_dst[keep_idx], e_w[keep_idx]
        picked_src.append(e_src)
        picked_dst.append(e_dst)
        picked_w.append(e_w)
        # union: min-label propagation over the picked rep-graph edges with
        # pointer jumping, iterated to fixpoint (a one-shot min scatter
        # loses chain/star merges — same min-equivalence iteration as
        # label/merge_labels.cuh)
        cu, cv = col[e_src], col[e_dst]
        lbl = np.arange(n, dtype=col.dtype)
        while True:
            before = lbl.copy()
            m = np.minimum(lbl[cu], lbl[cv])
            np.minimum.at(lbl, cu, m)
            np.minimum.at(lbl, cv, m)
            while True:
                nxt = lbl[lbl]
                if (nxt == lbl).all():
                    break
                lbl = nxt
            if (lbl == before).all():
                break
        color = jnp.asarray(lbl[col])

    if picked_src:
        out_src = jnp.asarray(np.concatenate(picked_src), jnp.int32)
        out_dst = jnp.asarray(np.concatenate(picked_dst), jnp.int32)
        out_w = jnp.asarray(np.concatenate(picked_w))
    else:
        out_src = jnp.zeros((0,), jnp.int32)
        out_dst = jnp.zeros((0,), jnp.int32)
        out_w = jnp.zeros((0,), w.dtype)
    return MSTResult(GraphCOO(out_src, out_dst, out_w, int(out_src.shape[0])),
                     color)
