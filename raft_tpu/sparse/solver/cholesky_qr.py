"""Cholesky-QR orthonormalization.

(ref: cpp/include/raft/sparse/solver/detail/cholesky_qr.cuh (159 LoC) —
``cholesky_qr2``: Q = Y R⁻¹ with R from chol(YᵀY), applied twice for
numerical robustness; the orthonormalization kernel of the randomized
sparse SVD.) Pure MXU work on TPU: one syrk-shaped matmul + a triangular
solve per pass.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular


def cholesky_qr(Y) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-pass Cholesky QR: returns (Q, R)."""
    Y = jnp.asarray(Y)
    G = Y.T @ Y
    # jitter for near-rank-deficient sketches (the reference relies on the
    # second pass to clean up; the jitter guards chol failure outright)
    eps = jnp.finfo(Y.dtype).eps * jnp.trace(G)
    R = jnp.linalg.cholesky(G + eps * jnp.eye(G.shape[0], dtype=Y.dtype)).T
    Q = solve_triangular(R.T, Y.T, lower=True).T
    return Q, R


def cholesky_qr2(Y) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-pass Cholesky QR (CholeskyQR2). (ref: detail/cholesky_qr.cuh)"""
    Q1, R1 = cholesky_qr(Y)
    Q, R2 = cholesky_qr(Q1)
    return Q, R2 @ R1
