"""Tiled-ELL sparse format — the TPU-native SpMV preprocessing.

(ref: the cusparse SpMV/SpMM surface
cpp/include/raft/sparse/detail/cusparse_wrappers.h:1 and the Lanczos SpMV
dispatch cpp/include/raft/sparse/solver/detail/lanczos.cuh:263-271. The
reference leans on cusparse's CSR kernels; TPU has no hardware
gather/scatter worth leaning on, so the format is re-thought: nonzeros are
re-laid-out ONCE, host-side, into fixed-size chunks whose column (resp.
row) footprint is a single tile — turning SpMV's irregular access into
per-chunk lane-select folds that Mosaic lowers to plain VPU compare/
select/reduce. See raft_tpu.ops.spmv_pallas for the kernels.)

Layout produced by :func:`tile_csr`:

- nonzeros grouped by (column tile, row tile) bucket, column-tile-major —
  within a bucket they keep stable INPUT order (a single-key stable sort
  on the bucket id; they are NOT sorted by row within a tile, which no
  consumer requires — the fold is order-insensitive within a bucket) —
  padded per column tile to a multiple of ``E`` (pad entries carry value
  0 → contribute nothing); stored as ``[n_chunks, E]`` arrays of values,
  LOCAL column ids (col % C) and global row ids. ``chunk_col_tile
  [n_chunks]`` maps each chunk to its x-tile (the Pallas scalar-prefetch
  block index).
- the same nonzeros re-grouped by row-tile bucket (stable ⇒
  column-tile-minor within a row tile, input order within a bucket), with
  ``perm [n_chunks·E]`` being the gather permutation from col-grouped
  contribution order to row-grouped order, ``row_local`` the in-tile row
  ids, and ``chunk_row_tile`` the per-chunk output tile index.

Conversion is one-time host work (like the reference's native cusparse
conversion routines): the default path is the C++ layout pass in
cpp/hostops.cpp (bucket-by-tile + per-tile sorts), with a bit-identical
numpy fallback when no toolchain is available; the arrays then live on
device.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledELL:
    """Device-resident tiled layout for one sparse matrix (see module doc).
    Registered as a pytree (array fields are leaves, geometry is static)
    so it can flow through jitted solver loops like the other sparse
    types."""

    shape: Tuple[int, int]
    C: int                      # column tile width (x tile length)
    R: int                      # row tile width (y tile length)
    E: int                      # chunk length (nonzeros per grid step)
    # --- gather phase (col-sorted) ---
    vals: jax.Array             # [n_chunks, E] f32
    col_local: jax.Array        # [n_chunks, E] int32, in [0, C)
    chunk_col_tile: jax.Array   # [n_chunks] int32
    # --- scatter phase (row-sorted) ---
    # perm bridges the two orderings. Two granularities:
    #   perm_rows [m_chunks·E/8] int32 — indices of 8-slot ROWS of the
    #     flat col-order (the default numpy layout buckets elements by
    #     (row tile, col tile) padded to 8-multiples so the bridge is a
    #     ROW gather: XLA's scalar gather measured 0.5 GB/s — 15.4 of
    #     the 17.1 ms SpMV at 2M nnz — while row gathers run ~50 GB/s);
    #     value n_chunks·E/8 points at an appended zero row (pads).
    #   perm [m_chunks, E] int32 — legacy scalar indices (the native C++
    #     layout pass); slower bridge, kept for fast host conversion.
    # Exactly one of the two is used by ops.spmv_pallas.spmv_tiled.
    perm: Optional[jax.Array]
    perm_rows: Optional[jax.Array]
    row_local: jax.Array        # [m_chunks, E] int32 in [0, R), pad = R
    chunk_row_tile: jax.Array   # [m_chunks] int32
    visited_row_tiles: jax.Array  # [n_row_tiles] bool — tiles with any nnz
    n_col_tiles: int
    n_row_tiles: int

    @property
    def n_chunks(self) -> int:
        return self.vals.shape[0]

    @property
    def m_chunks(self) -> int:
        return self.row_local.shape[0]

    _LEAVES = ("vals", "col_local", "chunk_col_tile", "perm", "perm_rows",
               "row_local", "chunk_row_tile", "visited_row_tiles")

    def tree_flatten(self):
        leaves = tuple(getattr(self, f) for f in self._LEAVES)
        aux = (self.shape, self.C, self.R, self.E,
               self.n_col_tiles, self.n_row_tiles)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, C, R, E, nct, nrt = aux
        return cls(shape, C, R, E, *leaves, n_col_tiles=nct,
                   n_row_tiles=nrt)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledPairs:
    """Device-resident (row tile × col tile)-bucketed layout of a sparsity
    STRUCTURE — the operand of the blocked SDDMM kernel
    (raft_tpu.ops.sddmm_pallas). Each chunk's E entries share one
    [R, C] block of the output, so the kernel can form that block's dense
    A·Bᵀ tile ON the MXU and fold the entries out of VMEM. ``pos`` maps
    each ORIGINAL structure entry to its chunk-flat slot, restoring the
    caller's nnz order after the kernel. ``rows``/``cols`` keep the
    original structure so the result can be returned as a sparse matrix."""

    shape: Tuple[int, int]
    R: int
    C: int
    E: int
    row_local: jax.Array        # [m_chunks, E] int32 in [0, R), pad = R
    col_local: jax.Array        # [m_chunks, E] int32 in [0, C), pad = 0
    chunk_row_tile: jax.Array   # [m_chunks] int32
    chunk_col_tile: jax.Array   # [m_chunks] int32
    pos: jax.Array              # [nnz] int32 into chunk-flat order
    rows: jax.Array             # [nnz] int32 — original structure
    cols: jax.Array             # [nnz] int32
    n_row_tiles: int
    n_col_tiles: int

    @property
    def nnz(self) -> int:
        return self.rows.shape[0]

    @property
    def m_chunks(self) -> int:
        return self.row_local.shape[0]

    _LEAVES = ("row_local", "col_local", "chunk_row_tile", "chunk_col_tile",
               "pos", "rows", "cols")

    def tree_flatten(self):
        leaves = tuple(getattr(self, f) for f in self._LEAVES)
        aux = (self.shape, self.R, self.C, self.E,
               self.n_row_tiles, self.n_col_tiles)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, R, C, E, nrt, nct = aux
        return cls(shape, R, C, E, *leaves, n_row_tiles=nrt,
                   n_col_tiles=nct)


def _checked_coo_parts(A, C: int, R: int, E: int, name: str):
    """Shared validation + extraction for the tiled conversions: kernel
    alignment check, CSR/COO (rows, cols, vals, shape) extraction, and
    id-range validation."""
    if E % 512 or C % 128 or R % 8:
        raise ValueError(f"{name}: need E % 512 == 0, C % 128 == 0, "
                         f"R % 8 == 0 (kernel fold/tile alignment)")
    if isinstance(A, CSRMatrix):
        rows = np.asarray(A.row_ids())
        cols = np.asarray(A.indices)
        vals = np.asarray(A.values, np.float32)
        shape = A.shape
    elif isinstance(A, COOMatrix):
        rows = np.asarray(A.rows)
        cols = np.asarray(A.cols)
        vals = np.asarray(A.values, np.float32)
        shape = A.shape
    else:
        raise TypeError(f"{name}: expected sparse matrix, got {type(A)}")
    if len(rows) and (
            int(rows.min()) < 0 or int(cols.min()) < 0
            or int(rows.max()) >= shape[0] or int(cols.max()) >= shape[1]):
        raise ValueError(
            f"{name}: row/col ids out of range for shape {shape}")
    return rows, cols, vals, shape


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledPairsSpmv:
    """Pair-tiled SpMV operand: a :class:`TiledPairs` structure layout
    plus the matrix VALUES in chunk-flat order and the row-tile visited
    mask. Consumed by raft_tpu.ops.spmv_pallas.spmv_pair_tiled — ONE
    fused gather·multiply·scatter kernel with no permutation pass (the
    TiledELL pipeline's XLA scalar permutation measured 15.4 of its
    17.1 ms at 2M nnz on v5e). Build with :func:`tile_csr_pairs`."""

    pairs: TiledPairs
    vals: jax.Array             # [m_chunks, 1, E] f32, pad entries 0
    visited: jax.Array          # [n_row_tiles] bool — tiles the grid writes

    @property
    def shape(self):
        return self.pairs.shape

    def tree_flatten(self):
        return (self.pairs, self.vals, self.visited), ()

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


@instrument("sparse.tile_csr_pairs")
def tile_csr_pairs(A, R: int = 256, C: int = 512, E: int = 2048,
                   impl: str = "auto") -> TiledPairsSpmv:
    """One-time conversion of a sparse MATRIX (values included) to the
    pair-tiled SpMV operand (see :class:`TiledPairsSpmv`)."""
    pairs = tile_pairs(A, R=R, C=C, E=E, impl=impl)
    # values come straight from the matrix in the SAME entry order
    # tile_pairs' pos maps (no second O(nnz) extraction pass)
    vals = np.asarray(A.values, np.float32)
    flat = jnp.zeros(pairs.m_chunks * pairs.E, jnp.float32)
    if len(vals):
        flat = flat.at[pairs.pos].set(jnp.asarray(vals))
    visited = jnp.zeros(pairs.n_row_tiles, bool).at[
        pairs.chunk_row_tile].set(True)
    blowup = pairs.m_chunks * pairs.E / max(1, pairs.nnz)
    if pairs.nnz > 0 and blowup > 4:
        from raft_tpu.core.logger import log_warn

        log_warn(
            "tile_csr_pairs: %.0fx pad blowup (%d slots for %d nnz) — "
            "the pair layout only wins for block-clustered structures; "
            "use prepare_spmv(layout='ell') for scattered matrices",
            blowup, pairs.m_chunks * pairs.E, pairs.nnz)
    return TiledPairsSpmv(pairs=pairs,
                          vals=flat.reshape(pairs.m_chunks, 1, pairs.E),
                          visited=visited)


def tile_pairs(structure, R: int = 256, C: int = 512,
               E: int = 2048, impl: str = "auto") -> TiledPairs:
    """Bucket a sparsity structure by (row tile, col tile) — one-time host
    conversion for the blocked SDDMM kernel. (ref: the preprocessing role
    of cusparse's SDDMM descriptors, cusparse_wrappers.h sddmm.)

    ``impl``: "auto" uses the native C++ layout pass when available,
    "numpy" forces the fallback; both produce BIT-IDENTICAL layouts
    (tested).

    Plans for large structures persist ACROSS PROCESSES through
    :mod:`raft_tpu.sparse.plan_cache` (the 39.8 s pairs prepare at the
    SPMV_BENCH 2M-nnz scale becomes a ~ms ``np.load`` on the second
    process), keyed purely by the sparsity structure — the pair layout
    carries no values."""
    if impl not in ("auto", "numpy"):
        raise ValueError(f"tile_pairs: impl must be 'auto' or 'numpy', "
                         f"got {impl!r}")
    rows, cols, _, shape = _checked_coo_parts(structure, C, R, E,
                                              "tile_pairs")
    from raft_tpu.sparse import plan_cache

    fp = None
    if plan_cache.enabled_for(len(rows)):
        fp = plan_cache.structure_fingerprint("pairs", shape, (R, C, E),
                                              rows, cols)
        plan = plan_cache.load_plan(fp)
        if plan is not None:
            m_chunks = plan["row_local"].shape[0] // E
            return TiledPairs(
                shape=shape, R=R, C=C, E=E,
                row_local=jnp.asarray(plan["row_local"].reshape(
                    m_chunks, E)),
                col_local=jnp.asarray(plan["col_local"].reshape(
                    m_chunks, E)),
                chunk_row_tile=jnp.asarray(plan["chunk_row_tile"]),
                chunk_col_tile=jnp.asarray(plan["chunk_col_tile"]),
                pos=jnp.asarray(plan["pos"]),
                rows=jnp.asarray(rows, jnp.int32),
                cols=jnp.asarray(cols, jnp.int32),
                n_row_tiles=max(1, -(-shape[0] // R)),
                n_col_tiles=max(1, -(-shape[1] // C)))
    out = _tile_pairs_impl(rows, cols, shape, R, C, E, impl)
    if fp is not None:
        plan_cache.save_plan(fp, {
            "row_local": np.asarray(out.row_local).reshape(-1),
            "col_local": np.asarray(out.col_local).reshape(-1),
            "chunk_row_tile": np.asarray(out.chunk_row_tile),
            "chunk_col_tile": np.asarray(out.chunk_col_tile),
            "pos": np.asarray(out.pos),
        })
    return out


def _tile_pairs_impl(rows, cols, shape, R: int, C: int, E: int,
                     impl: str) -> TiledPairs:
    n_row_tiles = max(1, -(-shape[0] // R))
    n_col_tiles = max(1, -(-shape[1] // C))

    if impl == "auto" and len(rows):
        from raft_tpu import native

        out = native.pair_layout(rows, cols, shape[0], shape[1], R, C, E)
        if out is not None:
            rloc, cloc, crt, cct, pos = out
            m_chunks = len(rloc) // E
            return TiledPairs(
                shape=shape, R=R, C=C, E=E,
                row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
                col_local=jnp.asarray(cloc.reshape(m_chunks, E)),
                chunk_row_tile=jnp.asarray(crt),
                chunk_col_tile=jnp.asarray(cct),
                pos=jnp.asarray(pos),
                rows=jnp.asarray(rows, jnp.int32),
                cols=jnp.asarray(cols, jnp.int32),
                n_row_tiles=n_row_tiles, n_col_tiles=n_col_tiles)

    key = (rows // R).astype(np.int64) * n_col_tiles + cols // C
    order = np.lexsort((cols, rows, key))
    pad_idx, chunk_key = _pad_groups(order, key, E)
    gr, gc = rows, cols                          # gather targets
    if len(pad_idx) == 0:                        # empty structure
        pad_idx = np.full(E, -1, np.int64)
        chunk_key = np.zeros(1, np.int32)
        gr = np.zeros(1, np.int64)               # dummy targets for the
        gc = np.zeros(1, np.int64)               # all-pad chunk
    safe = np.maximum(pad_idx, 0)
    rloc = np.where(pad_idx >= 0, gr[safe] % R, R).astype(np.int32)
    cloc = np.where(pad_idx >= 0, gc[safe] % C, 0).astype(np.int32)
    pos = np.empty(len(rows), np.int32)
    real = pad_idx >= 0
    pos[pad_idx[real]] = np.flatnonzero(real).astype(np.int32)
    m_chunks = len(pad_idx) // E
    return TiledPairs(
        shape=shape, R=R, C=C, E=E,
        row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
        col_local=jnp.asarray(cloc.reshape(m_chunks, E)),
        chunk_row_tile=jnp.asarray(
            (chunk_key // n_col_tiles).astype(np.int32)),
        chunk_col_tile=jnp.asarray(
            (chunk_key % n_col_tiles).astype(np.int32)),
        pos=jnp.asarray(pos),
        rows=jnp.asarray(rows, jnp.int32),
        cols=jnp.asarray(cols, jnp.int32),
        n_row_tiles=n_row_tiles, n_col_tiles=n_col_tiles,
    )


def _pad_groups(order, keys, E):
    """Given sort order and group key per nnz (keys[order] nondecreasing),
    pad each group's entries to a multiple of E. Returns (padded index
    array with -1 for pads, group id per chunk). Vectorized — conversion
    must stay O(nnz) numpy time, not Python-loop time."""
    n = len(order)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int32)
    sorted_keys = np.asarray(keys)[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    counts = np.diff(np.append(starts, n))
    padded_counts = -(-counts // E) * E
    out_starts = np.concatenate([[0], np.cumsum(padded_counts)[:-1]])
    total = int(padded_counts.sum())
    idx = np.full(total, -1, np.int64)
    # destination of each real entry: its group's padded start + rank
    ranks = np.arange(n) - np.repeat(starts, counts)
    idx[np.repeat(out_starts, counts) + ranks] = order
    chunk_tile = np.repeat(uniq, padded_counts // E).astype(np.int32)
    return idx, chunk_tile


@functools.partial(
    jax.jit, static_argnames=("C", "R", "E", "n_ct", "n_rt", "NG", "NM"))
def _tile_csr_device_core(rows, cols, vals, C: int, R: int, E: int,
                          n_ct: int, n_rt: int, NG: int, NM: int):
    """Device-side v2 tiled-ELL layout, mirroring the numpy pass above
    step for step (same stable sort keys ⇒ identical layout). Output
    arrays are sized to the STATIC worst-case bounds NG/NM (jit needs
    static shapes; padding inflates only by ≤7 slots per occupied
    bucket + one E-chunk per tile group); the wrapper fetches the two
    true sizes (the only host sync) and slices. Exists because the
    host conversion's device↔host transfers measured 3.8 s of config
    4's ~4.5 s at 2M nnz on the tunneled v5e.

    Ids are range-validated ON DEVICE, with the verdict fetched in the
    same host sync as the output sizes — the host paths' ValueError
    contract is preserved at no extra round trip."""
    nnz = rows.shape[0]
    ct = cols // C
    rt = rows // R
    bucket = ct * n_rt + rt                          # ct-major key
    # single-key stable sort (vs the old 3-key lexsort = 3 sort passes):
    # conversion was config 4's dominant cost — 0.89 s warm vs ~0.6 s
    # solve at 2M nnz (round-3 profile); within-bucket order is the
    # input order in all three layout passes
    order_g = jnp.argsort(bucket, stable=True)
    bsorted = bucket[order_g]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             bsorted[1:] != bsorted[:-1]])
    bidx = jnp.cumsum(first.astype(jnp.int32)) - 1   # dense bucket index
    nb = bidx[-1] + 1                                # traced bucket count
    barange = jnp.arange(nnz, dtype=jnp.int32)
    bvalid = barange < nb
    counts = jax.ops.segment_sum(jnp.ones((nnz,), jnp.int32), bidx,
                                 num_segments=nnz)
    bstart = jax.ops.segment_min(barange, bidx, num_segments=nnz)
    padded = (counts + 7) // 8 * 8
    b_off8 = jnp.cumsum(padded) - padded             # exclusive cumsum
    within = barange - bstart[bidx]
    g_slot8 = b_off8[bidx] + within                  # per element

    ub = jax.ops.segment_max(bsorted, bidx, num_segments=nnz)
    ub_ct = jnp.where(bvalid, ub // n_rt, n_ct - 1)
    # per-col-tile 8-padded sizes → E-padded group offsets
    ct_sizes8 = jax.ops.segment_sum(jnp.where(bvalid, padded, 0), ub_ct,
                                    num_segments=n_ct)
    ct_start8 = jnp.cumsum(ct_sizes8) - ct_sizes8
    grp_padded = -(-ct_sizes8 // E) * E
    grp_foff = jnp.cumsum(grp_padded) - grp_padded
    n_gather = jnp.sum(grp_padded)
    elem_final = grp_foff[ct[order_g]] + (g_slot8 - ct_start8[ct[order_g]])

    pv = jnp.zeros((NG,), vals.dtype).at[elem_final].set(vals[order_g])
    pc = jnp.zeros((NG,), jnp.int32).at[elem_final].set(
        (cols[order_g] % C).astype(jnp.int32))
    # chunk j's col tile: the group that owns slot j·E
    ch_arange = jnp.arange(NG // E, dtype=jnp.int32)
    chunk_col_tile = jnp.searchsorted(
        jnp.cumsum(grp_padded), ch_arange * E, side="right"
    ).astype(jnp.int32)

    # per-bucket start row in the FINAL gather stream
    bucket_final0 = grp_foff[ub_ct] + (b_off8 - ct_start8[ub_ct])
    bucket_row0 = bucket_final0 // 8

    # scatter stream: buckets rt-major (stable ⇒ ct-minor within rt)
    key2 = jnp.where(bvalid, (ub % n_rt) * n_ct + ub // n_rt,
                     jnp.iinfo(jnp.int32).max)
    order_b = jnp.argsort(key2, stable=True)         # invalid sort last
    sc_sizes = jnp.where(bvalid, padded, 0)[order_b]
    sc_rows = sc_sizes // 8
    sc_rt = jnp.where(bvalid[order_b], ub[order_b] % n_rt, n_rt - 1)
    rt_slots = jax.ops.segment_sum(sc_sizes, sc_rt, num_segments=n_rt)
    rt_padded = -(-rt_slots // E) * E
    rt_foff = jnp.cumsum(rt_padded) - rt_padded
    m_slots = jnp.sum(rt_padded)
    chunk_row_tile = jnp.searchsorted(
        jnp.cumsum(rt_padded), jnp.arange(NM // E, dtype=jnp.int32) * E,
        side="right").astype(jnp.int32)

    # per-bucket (scatter order) destination slot
    csc = jnp.cumsum(sc_sizes) - sc_sizes            # excl. cumsum
    rt_bstart_slots = jax.ops.segment_min(
        jnp.where(bvalid[order_b], csc, jnp.iinfo(jnp.int32).max),
        sc_rt, num_segments=n_rt)
    dst_slot0 = rt_foff[sc_rt] + (csc - rt_bstart_slots[sc_rt])
    dst_row0 = dst_slot0 // 8
    src_row0 = bucket_row0[order_b]

    # perm_rows: virtual scatter 8-row v belongs to scatter-bucket
    # searchsorted(cumsum(sc_rows), v, right); rows beyond the data or
    # in pad gaps point at the appended zero row
    zero_row = n_gather // 8
    csr_rows = jnp.cumsum(sc_rows)
    v8 = jnp.arange(NM // 8, dtype=jnp.int32)
    owner = jnp.searchsorted(csr_rows, v8, side="right").astype(jnp.int32)
    owner_c = jnp.minimum(owner, nnz - 1)
    within_rows = v8 - (csr_rows[owner_c] - sc_rows[owner_c])
    dstr = dst_row0[owner_c] + within_rows
    srcr = src_row0[owner_c] + within_rows
    have = (owner < nnz) & bvalid[order_b][owner_c]
    perm_rows = jnp.full((NM // 8,), zero_row, jnp.int32)
    perm_rows = perm_rows.at[jnp.where(have, dstr, NM // 8)].set(
        jnp.where(have, srcr, zero_row).astype(jnp.int32), mode="drop")

    # row_local: element destinations (bucket dst + within-bucket slot)
    inv_sc = jnp.zeros((nnz,), jnp.int32).at[order_b].set(
        jnp.arange(nnz, dtype=jnp.int32))
    elem_dst = dst_slot0[inv_sc[bidx]] + within
    rloc = jnp.full((NM,), R, jnp.int32).at[elem_dst].set(
        (rows[order_g] % R).astype(jnp.int32))

    visited = jnp.zeros((n_rt,), bool).at[
        jnp.where(bvalid, ub % n_rt, n_rt)].set(True, mode="drop")
    return (pv, pc, chunk_col_tile, perm_rows, rloc, chunk_row_tile,
            visited, n_gather, m_slots)


@jax.jit
def _ids_in_range(rows, cols, n_rows, n_cols):
    return (jnp.all((rows >= 0) & (rows < n_rows))
            & jnp.all((cols >= 0) & (cols < n_cols)))


def tile_csr_device(A, C: int = 512, R: int = 256,
                    E: int = 2048) -> TiledELL:
    """Device-side tiled-ELL conversion (see _tile_csr_device_core):
    the big arrays never cross the host boundary — only two size
    scalars sync. Produces the SAME layout as the numpy/native host
    passes (identical stable sort keys; asserted in tests)."""
    if isinstance(A, CSRMatrix):
        rows = A.row_ids()
        cols, vals, shape = A.indices, A.values, A.shape
    elif isinstance(A, COOMatrix):
        rows, cols, vals, shape = A.rows, A.cols, A.values, A.shape
    else:
        raise TypeError(f"tile_csr_device: expected sparse matrix, "
                        f"got {type(A)}")
    if E % 512 or C % 128 or R % 8:
        raise ValueError("tile_csr_device: need E % 512 == 0, "
                         "C % 128 == 0, R % 8 == 0")
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = jnp.asarray(vals, jnp.float32)
    nnz = int(rows.shape[0])
    n_ct = max(1, -(-shape[1] // C))
    n_rt = max(1, -(-shape[0] // R))
    if nnz == 0 or n_ct * n_rt >= 2 ** 31:
        return tile_csr(A, C=C, R=R, E=E, impl="numpy")
    # static worst-case stream bounds: ≤7 pad slots per occupied bucket
    # plus up to one E-chunk of pad per OCCUPIED tile group — empty
    # tiles contribute zero pad in the core (their segment sums round
    # up to 0), so the bound uses min(tiles, nnz), not the raw tile
    # count: a 10M×10M shape with 1k nnz must not allocate one E-chunk
    # for each of its ~20k col tiles
    nb_max = min(nnz, n_ct * n_rt)
    ns8 = nnz + 7 * nb_max
    occ_ct = min(n_ct, nnz)
    occ_rt = min(n_rt, nnz)
    NG = (-(-(ns8 + (E - 8) * occ_ct) // E)) * E
    NM = (-(-(ns8 + (E - 8) * occ_rt) // E)) * E
    out = _tile_csr_device_core(rows, cols, vals, C, R, E, n_ct, n_rt,
                                NG, NM)
    (pv, pc, cct, perm_rows, rloc, crt, visited, n_gather, m_slots) = out
    ok = _ids_in_range(rows, cols, shape[0], shape[1])
    # the ONLY host sync: two size scalars + the validation verdict
    ok, n_gather, m_slots = (bool(ok), int(n_gather), int(m_slots))
    if not ok:
        raise ValueError(
            f"tile_csr_device: row/col ids out of range for shape "
            f"{shape}")
    n_chunks = n_gather // E
    m_chunks = m_slots // E
    return TiledELL(
        shape=shape, C=C, R=R, E=E,
        vals=pv[:n_gather].reshape(n_chunks, E),
        col_local=pc[:n_gather].reshape(n_chunks, E),
        chunk_col_tile=cct[:n_chunks],
        perm=None,
        perm_rows=perm_rows[:m_slots // 8],
        row_local=rloc[:m_slots].reshape(m_chunks, E),
        chunk_row_tile=crt[:m_chunks],
        visited_row_tiles=visited,
        n_col_tiles=n_ct, n_row_tiles=n_rt)


@instrument("sparse.tile_csr")
def tile_csr(A, C: int = 512, R: int = 256, E: int = 2048,
             impl: str = "auto") -> TiledELL:
    """Convert a CSR/COO matrix to the tiled-ELL layout (one-time, host).

    ``impl``: "auto" builds the v2 8-aligned-bucket layout (ROW-gather
    bridge — runtime-optimal: the legacy scalar-permutation bridge
    measured 15.4 of the 17.1 ms SpMV at 2M nnz on v5e): ON DEVICE
    when an accelerator backend is active (tile_csr_device — the host
    passes' device↔host transfers measured 3.8 s of config 4 at 2M nnz
    on the tunneled v5e), else via the native C++ pass, else numpy —
    all three BIT-IDENTICAL (tested); "device"/"numpy" force those;
    "native" forces the LEGACY scalar-perm C++ layout (kept for
    comparison/compat). All layouts produce identical SpMV results
    (tested)."""
    fault_point("tile_csr")
    if impl not in ("auto", "device", "numpy", "native"):
        raise ValueError(f"tile_csr: impl must be 'auto', 'device', "
                         f"'numpy' or 'native', got {impl!r}")
    if impl == "device" or (
            impl == "auto" and jax.default_backend() != "cpu"):
        # the device conversion exists because HOST↔device transfers
        # dominate it — a disk cache would reintroduce the host round
        # trip, so only the host layout passes persist
        return tile_csr_device(A, C=C, R=R, E=E)
    coo_rows, coo_cols, vals, shape = _checked_coo_parts(A, C, R, E,
                                                         "tile_csr")
    # persistent plan cache: keyed by the sparsity STRUCTURE; the
    # tiled-ELL arrays bake values in, so the stored plan carries a
    # values digest and a different-values lookup is an honest miss
    from raft_tpu.sparse import plan_cache

    fp = vd = None
    if plan_cache.enabled_for(len(coo_rows)):
        kind = "ell-legacy" if impl == "native" else "ell-v2"
        fp = plan_cache.structure_fingerprint(kind, shape, (C, R, E),
                                              coo_rows, coo_cols)
        vd = plan_cache.values_digest(vals)
        plan = plan_cache.load_plan(fp, vals_digest=vd)
        if plan is not None:
            return _tiled_ell_from_plan(plan, shape, C, R, E)
    out = _tile_csr_host(coo_rows, coo_cols, vals, shape, C, R, E, impl)
    if fp is not None:
        plan_cache.save_plan(fp, _tiled_ell_plan_arrays(out),
                             vals_digest=vd)
    return out


def _tiled_ell_plan_arrays(t: TiledELL) -> dict:
    arrays = {
        "vals": np.asarray(t.vals).reshape(-1),
        "col_local": np.asarray(t.col_local).reshape(-1),
        "chunk_col_tile": np.asarray(t.chunk_col_tile),
        "row_local": np.asarray(t.row_local).reshape(-1),
        "chunk_row_tile": np.asarray(t.chunk_row_tile),
        "visited_row_tiles": np.asarray(t.visited_row_tiles),
    }
    if t.perm is not None:
        arrays["perm"] = np.asarray(t.perm).reshape(-1)
    if t.perm_rows is not None:
        arrays["perm_rows"] = np.asarray(t.perm_rows)
    return arrays


def _tiled_ell_from_plan(plan: dict, shape, C: int, R: int,
                         E: int) -> TiledELL:
    n_chunks = plan["vals"].size // E
    m_chunks = plan["row_local"].size // E
    return TiledELL(
        shape=shape, C=C, R=R, E=E,
        vals=jnp.asarray(plan["vals"].reshape(n_chunks, E)),
        col_local=jnp.asarray(plan["col_local"].reshape(n_chunks, E)),
        chunk_col_tile=jnp.asarray(plan["chunk_col_tile"]),
        perm=(jnp.asarray(plan["perm"].reshape(m_chunks, E))
              if "perm" in plan else None),
        perm_rows=(jnp.asarray(plan["perm_rows"])
                   if "perm_rows" in plan else None),
        row_local=jnp.asarray(plan["row_local"].reshape(m_chunks, E)),
        chunk_row_tile=jnp.asarray(plan["chunk_row_tile"]),
        visited_row_tiles=jnp.asarray(plan["visited_row_tiles"]),
        n_col_tiles=max(1, -(-shape[1] // C)),
        n_row_tiles=max(1, -(-shape[0] // R)))


def _tile_csr_host(coo_rows, coo_cols, vals, shape, C: int, R: int,
                   E: int, impl: str) -> TiledELL:
    """The host layout passes of :func:`tile_csr` (native v2 / native
    legacy / numpy v2), split out so the plan cache wraps all three
    return points at once."""
    if impl == "auto" and len(coo_rows):
        from raft_tpu import native

        out = native.tiled_layout_v2(coo_rows, coo_cols, vals, shape[0],
                                     shape[1], C, R, E)
        if out is not None:
            pv, pc, cct, perm_rows, rloc, crt, visited = out
            return TiledELL(
                shape=shape, C=C, R=R, E=E,
                vals=jnp.asarray(pv.reshape(-1, E)),
                col_local=jnp.asarray(pc.reshape(-1, E)),
                chunk_col_tile=jnp.asarray(cct),
                perm=None,
                perm_rows=jnp.asarray(perm_rows),
                row_local=jnp.asarray(rloc.reshape(-1, E)),
                chunk_row_tile=jnp.asarray(crt),
                visited_row_tiles=jnp.asarray(visited),
                n_col_tiles=max(1, -(-shape[1] // C)),
                n_row_tiles=max(1, -(-shape[0] // R)))

    if impl == "native" and len(coo_rows):
        from raft_tpu import native

        out = native.tiled_layout(coo_rows, coo_cols, vals, shape[0],
                                  shape[1], C, R, E)
        if out is not None:
            pv, pc, cct, perm, rloc, crt, visited = out
            return TiledELL(
                shape=shape, C=C, R=R, E=E,
                vals=jnp.asarray(pv.reshape(-1, E)),
                col_local=jnp.asarray(pc.reshape(-1, E)),
                chunk_col_tile=jnp.asarray(cct),
                perm=jnp.asarray(perm.reshape(-1, E)),
                perm_rows=None,
                row_local=jnp.asarray(rloc.reshape(-1, E)),
                chunk_row_tile=jnp.asarray(crt),
                visited_row_tiles=jnp.asarray(visited),
                n_col_tiles=max(1, -(-shape[1] // C)),
                n_row_tiles=max(1, -(-shape[0] // R)))

    # --- v2 numpy layout: (col tile, row tile)-bucketed, 8-ALIGNED ---
    # Elements are grouped into (col tile, row tile) buckets padded to
    # 8-slot multiples; the gather stream concatenates buckets ct-major,
    # the scatter stream rt-major — the SAME 8-slot rows in both — so
    # the gather→scatter bridge is a ROW gather (perm_rows). XLA's
    # scalar gather measured 0.5 GB/s (15.4 of 17.1 ms at 2M nnz);
    # 8-wide row gathers run ~50 GB/s. Scatter order adds the ct key
    # (legal: scatter-chunk internal order is irrelevant to the one-hot
    # accumulation).
    n_col_tiles = max(1, -(-shape[1] // C))
    n_row_tiles = max(1, -(-shape[0] // R))
    if len(coo_rows) == 0:                       # empty matrix
        return TiledELL(
            shape=shape, C=C, R=R, E=E,
            vals=jnp.zeros((1, E), jnp.float32),
            col_local=jnp.zeros((1, E), jnp.int32),
            chunk_col_tile=jnp.zeros(1, jnp.int32),
            perm=None,
            perm_rows=jnp.full(E // 8, E // 8, jnp.int32),  # all zero-row
            row_local=jnp.full((1, E), R, jnp.int32),
            chunk_row_tile=jnp.zeros(1, jnp.int32),
            visited_row_tiles=jnp.zeros(n_row_tiles, bool),
            n_col_tiles=n_col_tiles, n_row_tiles=n_row_tiles)

    ct = (coo_cols // C).astype(np.int64)
    rt = (coo_rows // R).astype(np.int64)
    bucket = ct * n_row_tiles + rt               # ct-major bucket key
    # stable single-key sort: within-bucket order = input order (chunk-
    # internal order is irrelevant to both SpMV phases) — one sort pass
    # instead of lexsort's three, same key in all three layout passes
    order_g = np.argsort(bucket, kind="stable")
    bsorted = bucket[order_g]
    ub, bstart = np.unique(bsorted, return_index=True)
    counts = np.diff(np.append(bstart, len(bsorted)))
    padded = ((counts + 7) // 8) * 8             # 8-aligned bucket sizes
    b_off8 = np.concatenate(([0], np.cumsum(padded)))[:-1]
    total8 = int(padded.sum())
    # element slot in the 8-padded (pre-chunk-pad) gather stream
    within = np.arange(len(bsorted)) - np.repeat(bstart, counts)
    g_slot8 = np.repeat(b_off8, counts) + within

    # chunk-pad the gather stream per col tile to E boundaries (E is a
    # multiple of 8, so 8-row alignment survives)
    slot_ct = np.repeat(ub // n_row_tiles, padded)
    grp_ids, grp_start = np.unique(slot_ct, return_index=True)
    grp_sizes = np.diff(np.append(grp_start, total8))
    grp_padded = ((grp_sizes + E - 1) // E) * E
    grp_foff = np.concatenate(([0], np.cumsum(grp_padded)))[:-1]
    grp_of_slot8 = np.repeat(np.arange(len(grp_ids)), grp_sizes)
    final_of_slot8 = (grp_foff[grp_of_slot8]
                      + (np.arange(total8) - grp_start[grp_of_slot8]))
    n_gather_slots = int(grp_padded.sum())
    n_chunks = n_gather_slots // E

    elem_final = final_of_slot8[g_slot8]
    pv = np.zeros(n_gather_slots, np.float32)
    pv[elem_final] = vals[order_g]
    pc = np.zeros(n_gather_slots, np.int32)
    pc[elem_final] = (coo_cols[order_g] % C).astype(np.int32)
    chunk_col_tile = np.repeat(grp_ids, grp_padded // E).astype(np.int32)

    # per-bucket start ROW in the final gather stream
    bucket_final_start = final_of_slot8[b_off8]
    bucket_row0 = bucket_final_start // 8        # 8-aligned by design

    # scatter stream: buckets reordered rt-major, then rt groups padded
    # to E with whole zero rows
    key2 = (ub % n_row_tiles) * n_col_tiles + (ub // n_row_tiles)
    order_b = np.argsort(key2, kind="stable")
    sc_sizes = padded[order_b]                   # per-bucket slot counts
    sc_rows = sc_sizes // 8
    sc_rt = (ub[order_b] % n_row_tiles).astype(np.int64)
    # per-rt-group sizes in the bucket-concat scatter stream
    rt_ids, rt_start = np.unique(sc_rt, return_index=True)
    # rt_start indexes buckets; convert to slot counts per rt group
    slots_per_rt = np.add.reduceat(sc_sizes, rt_start)
    rt_padded = ((slots_per_rt + E - 1) // E) * E
    m_chunks = int(rt_padded.sum()) // E
    chunk_row_tile = np.repeat(rt_ids, rt_padded // E).astype(np.int32)

    zero_row = n_gather_slots // 8               # appended zero 8-row
    perm_rows = np.full(m_chunks * E // 8, zero_row, np.int32)
    rloc = np.full(m_chunks * E, R, np.int32)
    # destination offsets: per rt group start + running position of each
    # bucket inside its group
    rt_foff = np.concatenate(([0], np.cumsum(rt_padded)))[:-1]
    rt_of_bucket = np.repeat(np.arange(len(rt_ids)),
                             np.diff(np.append(rt_start, len(order_b))))
    within_rt = (np.concatenate(([0], np.cumsum(sc_sizes)))[:-1]
                 - np.repeat(np.concatenate(
                     ([0], np.cumsum(sc_sizes)))[:-1][rt_start],
                     np.diff(np.append(rt_start, len(order_b)))))
    dst_slot0 = rt_foff[rt_of_bucket] + within_rt    # per bucket
    # fill perm_rows: bucket b (scatter order) occupies rows
    # dst_slot0//8 .. +sc_rows, sourcing gather rows bucket_row0[order_b]
    dst_row0 = dst_slot0 // 8
    src_row0 = bucket_row0[order_b]
    row_fill = np.repeat(dst_row0, sc_rows) + (
        np.arange(int(sc_rows.sum()))
        - np.repeat(np.concatenate(([0], np.cumsum(sc_rows)))[:-1],
                    sc_rows))
    src_fill = np.repeat(src_row0, sc_rows) + (
        np.arange(int(sc_rows.sum()))
        - np.repeat(np.concatenate(([0], np.cumsum(sc_rows)))[:-1],
                    sc_rows))
    perm_rows[row_fill] = src_fill.astype(np.int32)
    # row_local: real elements land at (bucket dst + within-bucket slot)
    inv_bucket_pos = np.empty(len(ub), np.int64)
    inv_bucket_pos[order_b] = np.arange(len(order_b))
    elem_dst = (dst_slot0[inv_bucket_pos][np.searchsorted(ub, bsorted)]
                + within)
    rloc[elem_dst] = (coo_rows[order_g] % R).astype(np.int32)

    visited = np.zeros(n_row_tiles, bool)
    visited[np.asarray(chunk_row_tile, np.int64)] = True
    return TiledELL(
        shape=shape, C=C, R=R, E=E,
        vals=jnp.asarray(pv.reshape(n_chunks, E)),
        col_local=jnp.asarray(pc.reshape(n_chunks, E)),
        chunk_col_tile=jnp.asarray(chunk_col_tile),
        perm=None,
        perm_rows=jnp.asarray(perm_rows),
        row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
        chunk_row_tile=jnp.asarray(chunk_row_tile),
        visited_row_tiles=jnp.asarray(visited),
        n_col_tiles=n_col_tiles, n_row_tiles=n_row_tiles,
    )


