"""Tiled-ELL sparse format — the TPU-native SpMV preprocessing.

(ref: the cusparse SpMV/SpMM surface
cpp/include/raft/sparse/detail/cusparse_wrappers.h:1 and the Lanczos SpMV
dispatch cpp/include/raft/sparse/solver/detail/lanczos.cuh:263-271. The
reference leans on cusparse's CSR kernels; TPU has no hardware
gather/scatter worth leaning on, so the format is re-thought: nonzeros are
re-laid-out ONCE, host-side, into fixed-size chunks whose column (resp.
row) footprint is a single tile — turning SpMV's irregular access into
per-chunk lane-select folds that Mosaic lowers to plain VPU compare/
select/reduce. See raft_tpu.ops.spmv_pallas for the kernels.)

Layout produced by :func:`tile_csr`:

- nonzeros sorted by (column tile, then row), padded per column tile to a
  multiple of ``E`` (pad entries carry value 0 → contribute nothing);
  stored as ``[n_chunks, E]`` arrays of values, LOCAL column ids
  (col % C) and global row ids. ``chunk_col_tile [n_chunks]`` maps each
  chunk to its x-tile (the Pallas scalar-prefetch block index).
- the same nonzeros re-sorted by (row tile, then row), with
  ``perm [n_chunks·E]`` being the gather permutation from col-sorted
  contribution order to row-sorted order, ``row_local`` the in-tile row
  ids, and ``chunk_row_tile`` the per-chunk output tile index.

Conversion is one-time host work (like the reference's native cusparse
conversion routines): the default path is the C++ layout pass in
cpp/hostops.cpp (bucket-by-tile + per-tile sorts), with a bit-identical
numpy fallback when no toolchain is available; the arrays then live on
device.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledELL:
    """Device-resident tiled layout for one sparse matrix (see module doc).
    Registered as a pytree (array fields are leaves, geometry is static)
    so it can flow through jitted solver loops like the other sparse
    types."""

    shape: Tuple[int, int]
    C: int                      # column tile width (x tile length)
    R: int                      # row tile width (y tile length)
    E: int                      # chunk length (nonzeros per grid step)
    # --- gather phase (col-sorted) ---
    vals: jax.Array             # [n_chunks, E] f32
    col_local: jax.Array        # [n_chunks, E] int32, in [0, C)
    chunk_col_tile: jax.Array   # [n_chunks] int32
    # --- scatter phase (row-sorted) ---
    perm: jax.Array             # [m_chunks, E] int32 into flat col-order
    row_local: jax.Array        # [m_chunks, E] int32 in [0, R), pad = R
    chunk_row_tile: jax.Array   # [m_chunks] int32
    visited_row_tiles: jax.Array  # [n_row_tiles] bool — tiles with any nnz
    n_col_tiles: int
    n_row_tiles: int

    @property
    def n_chunks(self) -> int:
        return self.vals.shape[0]

    @property
    def m_chunks(self) -> int:
        return self.row_local.shape[0]

    _LEAVES = ("vals", "col_local", "chunk_col_tile", "perm", "row_local",
               "chunk_row_tile", "visited_row_tiles")

    def tree_flatten(self):
        leaves = tuple(getattr(self, f) for f in self._LEAVES)
        aux = (self.shape, self.C, self.R, self.E,
               self.n_col_tiles, self.n_row_tiles)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, C, R, E, nct, nrt = aux
        return cls(shape, C, R, E, *leaves, n_col_tiles=nct,
                   n_row_tiles=nrt)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledPairs:
    """Device-resident (row tile × col tile)-bucketed layout of a sparsity
    STRUCTURE — the operand of the blocked SDDMM kernel
    (raft_tpu.ops.sddmm_pallas). Each chunk's E entries share one
    [R, C] block of the output, so the kernel can form that block's dense
    A·Bᵀ tile ON the MXU and fold the entries out of VMEM. ``pos`` maps
    each ORIGINAL structure entry to its chunk-flat slot, restoring the
    caller's nnz order after the kernel. ``rows``/``cols`` keep the
    original structure so the result can be returned as a sparse matrix."""

    shape: Tuple[int, int]
    R: int
    C: int
    E: int
    row_local: jax.Array        # [m_chunks, E] int32 in [0, R), pad = R
    col_local: jax.Array        # [m_chunks, E] int32 in [0, C), pad = 0
    chunk_row_tile: jax.Array   # [m_chunks] int32
    chunk_col_tile: jax.Array   # [m_chunks] int32
    pos: jax.Array              # [nnz] int32 into chunk-flat order
    rows: jax.Array             # [nnz] int32 — original structure
    cols: jax.Array             # [nnz] int32
    n_row_tiles: int
    n_col_tiles: int

    @property
    def nnz(self) -> int:
        return self.rows.shape[0]

    @property
    def m_chunks(self) -> int:
        return self.row_local.shape[0]

    _LEAVES = ("row_local", "col_local", "chunk_row_tile", "chunk_col_tile",
               "pos", "rows", "cols")

    def tree_flatten(self):
        leaves = tuple(getattr(self, f) for f in self._LEAVES)
        aux = (self.shape, self.R, self.C, self.E,
               self.n_row_tiles, self.n_col_tiles)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, R, C, E, nrt, nct = aux
        return cls(shape, R, C, E, *leaves, n_row_tiles=nrt,
                   n_col_tiles=nct)


def _checked_coo_parts(A, C: int, R: int, E: int, name: str):
    """Shared validation + extraction for the tiled conversions: kernel
    alignment check, CSR/COO (rows, cols, vals, shape) extraction, and
    id-range validation."""
    if E % 512 or C % 128 or R % 8:
        raise ValueError(f"{name}: need E % 512 == 0, C % 128 == 0, "
                         f"R % 8 == 0 (kernel fold/tile alignment)")
    if isinstance(A, CSRMatrix):
        rows = np.asarray(A.row_ids())
        cols = np.asarray(A.indices)
        vals = np.asarray(A.values, np.float32)
        shape = A.shape
    elif isinstance(A, COOMatrix):
        rows = np.asarray(A.rows)
        cols = np.asarray(A.cols)
        vals = np.asarray(A.values, np.float32)
        shape = A.shape
    else:
        raise TypeError(f"{name}: expected sparse matrix, got {type(A)}")
    if len(rows) and (
            int(rows.min()) < 0 or int(cols.min()) < 0
            or int(rows.max()) >= shape[0] or int(cols.max()) >= shape[1]):
        raise ValueError(
            f"{name}: row/col ids out of range for shape {shape}")
    return rows, cols, vals, shape


def tile_pairs(structure, R: int = 256, C: int = 512,
               E: int = 2048, impl: str = "auto") -> TiledPairs:
    """Bucket a sparsity structure by (row tile, col tile) — one-time host
    conversion for the blocked SDDMM kernel. (ref: the preprocessing role
    of cusparse's SDDMM descriptors, cusparse_wrappers.h sddmm.)

    ``impl``: "auto" uses the native C++ layout pass when available,
    "numpy" forces the fallback; both produce BIT-IDENTICAL layouts
    (tested)."""
    if impl not in ("auto", "numpy"):
        raise ValueError(f"tile_pairs: impl must be 'auto' or 'numpy', "
                         f"got {impl!r}")
    rows, cols, _, shape = _checked_coo_parts(structure, C, R, E,
                                              "tile_pairs")
    n_row_tiles = max(1, -(-shape[0] // R))
    n_col_tiles = max(1, -(-shape[1] // C))

    if impl == "auto" and len(rows):
        from raft_tpu import native

        out = native.pair_layout(rows, cols, shape[0], shape[1], R, C, E)
        if out is not None:
            rloc, cloc, crt, cct, pos = out
            m_chunks = len(rloc) // E
            return TiledPairs(
                shape=shape, R=R, C=C, E=E,
                row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
                col_local=jnp.asarray(cloc.reshape(m_chunks, E)),
                chunk_row_tile=jnp.asarray(crt),
                chunk_col_tile=jnp.asarray(cct),
                pos=jnp.asarray(pos),
                rows=jnp.asarray(rows, jnp.int32),
                cols=jnp.asarray(cols, jnp.int32),
                n_row_tiles=n_row_tiles, n_col_tiles=n_col_tiles)

    key = (rows // R).astype(np.int64) * n_col_tiles + cols // C
    order = np.lexsort((cols, rows, key))
    pad_idx, chunk_key = _pad_groups(order, key, E)
    gr, gc = rows, cols                          # gather targets
    if len(pad_idx) == 0:                        # empty structure
        pad_idx = np.full(E, -1, np.int64)
        chunk_key = np.zeros(1, np.int32)
        gr = np.zeros(1, np.int64)               # dummy targets for the
        gc = np.zeros(1, np.int64)               # all-pad chunk
    safe = np.maximum(pad_idx, 0)
    rloc = np.where(pad_idx >= 0, gr[safe] % R, R).astype(np.int32)
    cloc = np.where(pad_idx >= 0, gc[safe] % C, 0).astype(np.int32)
    pos = np.empty(len(rows), np.int32)
    real = pad_idx >= 0
    pos[pad_idx[real]] = np.flatnonzero(real).astype(np.int32)
    m_chunks = len(pad_idx) // E
    return TiledPairs(
        shape=shape, R=R, C=C, E=E,
        row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
        col_local=jnp.asarray(cloc.reshape(m_chunks, E)),
        chunk_row_tile=jnp.asarray(
            (chunk_key // n_col_tiles).astype(np.int32)),
        chunk_col_tile=jnp.asarray(
            (chunk_key % n_col_tiles).astype(np.int32)),
        pos=jnp.asarray(pos),
        rows=jnp.asarray(rows, jnp.int32),
        cols=jnp.asarray(cols, jnp.int32),
        n_row_tiles=n_row_tiles, n_col_tiles=n_col_tiles,
    )


def _pad_groups(order, keys, E):
    """Given sort order and group key per nnz (keys[order] nondecreasing),
    pad each group's entries to a multiple of E. Returns (padded index
    array with -1 for pads, group id per chunk). Vectorized — conversion
    must stay O(nnz) numpy time, not Python-loop time."""
    n = len(order)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int32)
    sorted_keys = np.asarray(keys)[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    counts = np.diff(np.append(starts, n))
    padded_counts = -(-counts // E) * E
    out_starts = np.concatenate([[0], np.cumsum(padded_counts)[:-1]])
    total = int(padded_counts.sum())
    idx = np.full(total, -1, np.int64)
    # destination of each real entry: its group's padded start + rank
    ranks = np.arange(n) - np.repeat(starts, counts)
    idx[np.repeat(out_starts, counts) + ranks] = order
    chunk_tile = np.repeat(uniq, padded_counts // E).astype(np.int32)
    return idx, chunk_tile


def tile_csr(A, C: int = 512, R: int = 256, E: int = 2048,
             impl: str = "auto") -> TiledELL:
    """Convert a CSR/COO matrix to the tiled-ELL layout (one-time, host).

    ``impl``: "auto" uses the native C++ layout pass when the hostops
    library is available (the reference keeps its conversions native too
    — cusparse conversion routines; ~an order of magnitude faster than
    numpy at RMAT scale), "numpy" forces the fallback. Both produce
    BIT-IDENTICAL layouts (tested)."""
    if impl not in ("auto", "numpy"):
        raise ValueError(f"tile_csr: impl must be 'auto' or 'numpy', "
                         f"got {impl!r}")
    coo_rows, coo_cols, vals, shape = _checked_coo_parts(A, C, R, E,
                                                         "tile_csr")

    if impl == "auto" and len(coo_rows):
        from raft_tpu import native

        out = native.tiled_layout(coo_rows, coo_cols, vals, shape[0],
                                  shape[1], C, R, E)
        if out is not None:
            pv, pc, cct, perm, rloc, crt, visited = out
            return TiledELL(
                shape=shape, C=C, R=R, E=E,
                vals=jnp.asarray(pv.reshape(-1, E)),
                col_local=jnp.asarray(pc.reshape(-1, E)),
                chunk_col_tile=jnp.asarray(cct),
                perm=jnp.asarray(perm.reshape(-1, E)),
                row_local=jnp.asarray(rloc.reshape(-1, E)),
                chunk_row_tile=jnp.asarray(crt),
                visited_row_tiles=jnp.asarray(visited),
                n_col_tiles=max(1, -(-shape[1] // C)),
                n_row_tiles=max(1, -(-shape[0] // R)))

    # --- gather phase: sort by (col tile, row) and pad per col tile ---
    col_tile = coo_cols // C
    order = np.lexsort((coo_rows, col_tile))
    pad_idx, chunk_col_tile = _pad_groups(order, col_tile, E)
    pv = np.where(pad_idx >= 0, vals[np.maximum(pad_idx, 0)], 0.0
                  ).astype(np.float32)
    pc = np.where(pad_idx >= 0, coo_cols[np.maximum(pad_idx, 0)] % C, 0
                  ).astype(np.int32)
    prow = np.where(pad_idx >= 0, coo_rows[np.maximum(pad_idx, 0)], -1)

    n_chunks = max(1, len(pad_idx) // E)
    if len(pad_idx) == 0:                        # empty matrix
        pv = np.zeros(E, np.float32)
        pc = np.zeros(E, np.int32)
        prow = np.full(E, -1, np.int64)
        chunk_col_tile = np.zeros(1, np.int32)

    # --- scatter phase: positions in flat col-order, sorted by (row tile,
    # row) with pads (prow = -1) sent to the end of their row tile ---
    flat_pos = np.arange(len(prow), dtype=np.int64)
    row_tile = np.where(prow >= 0, prow // R, shape[0] // R + 1)
    order2 = np.lexsort((prow, row_tile))
    # drop trailing all-pad entries beyond the last real one, then re-pad
    # per row tile
    real_mask = prow[order2] >= 0
    order2 = order2[real_mask]
    rt_keys = prow[order2] // R
    pad2, chunk_row_tile = _pad_groups(np.arange(len(order2)), rt_keys, E)
    src = np.where(pad2 >= 0, flat_pos[order2[np.maximum(pad2, 0)]], 0
                   ).astype(np.int32)
    rloc = np.where(pad2 >= 0, prow[order2[np.maximum(pad2, 0)]] % R, R
                    ).astype(np.int32)
    if len(pad2) == 0:
        src = np.zeros(E, np.int32)
        rloc = np.full(E, R, np.int32)
        chunk_row_tile = np.zeros(1, np.int32)
    # pads must contribute nothing: point them at a real slot but mark
    # row_local = R (outside every lane id, masked in-kernel)

    m_chunks = len(src) // E
    n_col_tiles = max(1, -(-shape[1] // C))
    n_row_tiles = max(1, -(-shape[0] // R))
    visited = np.zeros(n_row_tiles, bool)
    visited[np.asarray(chunk_row_tile, np.int64)] = True
    return TiledELL(
        shape=shape, C=C, R=R, E=E,
        vals=jnp.asarray(pv.reshape(n_chunks, E)),
        col_local=jnp.asarray(pc.reshape(n_chunks, E)),
        chunk_col_tile=jnp.asarray(chunk_col_tile),
        perm=jnp.asarray(src.reshape(m_chunks, E)),
        row_local=jnp.asarray(rloc.reshape(m_chunks, E)),
        chunk_row_tile=jnp.asarray(chunk_row_tile),
        visited_row_tiles=jnp.asarray(visited),
        n_col_tiles=n_col_tiles, n_row_tiles=n_row_tiles,
    )
