"""Sparse format conversions.

(ref: cpp/include/raft/sparse/convert/csr.cuh:202 (coo↔csr),
convert/coo.cuh, convert/dense.cuh, convert/detail/adj_to_csr.cuh,
convert/detail/bitmap_to_csr.cuh (344 LoC), detail/bitset_to_csr.cuh.)

TPU notes: conversions that preserve nnz (coo↔csr, sorting) are fully
vectorized jax (static shapes). Conversions that *discover* nnz
(dense→sparse, bitmap→csr) have data-dependent output shapes, which XLA
cannot express — those run through host numpy exactly once at data-prep
time (the reference likewise launches count kernels + allocs before its
fill kernels; here the host does the counting).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import BitmapView, BitsetView
from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix


def sorted_coo_to_csr(coo: COOMatrix) -> CSRMatrix:
    """COO (sorted by row) → CSR. (ref: convert/csr.cuh ``sorted_coo_to_csr``)"""
    counts = jnp.bincount(coo.rows, length=coo.shape[0])
    indptr = jnp.concatenate([jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)])
    return CSRMatrix(indptr.astype(jnp.int32), coo.cols, coo.values, coo.shape)


def coo_to_csr(coo: COOMatrix) -> CSRMatrix:
    """General COO → CSR (sorts by (row, col) first).
    (ref: convert/csr.cuh ``coo_to_csr``)"""
    order = jnp.lexsort((coo.cols, coo.rows))
    sorted_coo = COOMatrix(coo.rows[order], coo.cols[order], coo.values[order],
                           coo.shape)
    return sorted_coo_to_csr(sorted_coo)


def csr_to_coo(csr: CSRMatrix) -> COOMatrix:
    """(ref: convert/coo.cuh ``csr_to_coo`` — indptr expansion)"""
    return COOMatrix(csr.row_ids(), csr.indices, csr.values, csr.shape)


def dense_to_csr(dense) -> CSRMatrix:
    """(ref: convert/dense.cuh; host nnz discovery, see module note)"""
    return CSRMatrix.from_dense(np.asarray(dense))


def dense_to_coo(dense) -> COOMatrix:
    return COOMatrix.from_dense(np.asarray(dense))


def csr_to_dense(csr: CSRMatrix) -> jax.Array:
    """(ref: convert/dense.cuh ``csr_to_dense``)"""
    return csr.to_dense()


def coo_to_dense(coo: COOMatrix) -> jax.Array:
    return coo.to_dense()


def adj_to_csr(adj) -> CSRMatrix:
    """Boolean adjacency matrix → CSR of ones.
    (ref: convert/detail/adj_to_csr.cuh)"""
    adj = np.asarray(adj).astype(bool)
    r, c = np.nonzero(adj)
    indptr = np.zeros(adj.shape[0] + 1, np.int32)
    np.add.at(indptr, r + 1, 1)
    return CSRMatrix(jnp.asarray(np.cumsum(indptr, dtype=np.int32)),
                     jnp.asarray(c, jnp.int32),
                     jnp.ones((len(c),), jnp.float32), adj.shape)


def bitmap_to_csr(bitmap: BitmapView) -> CSRMatrix:
    """2-D bitmap → CSR of ones. (ref: convert/detail/bitmap_to_csr.cuh)"""
    dense = np.asarray(bitmap.to_dense())
    return adj_to_csr(dense)


def bitset_to_csr(bitset: BitsetView, n_repeat: int = 1) -> CSRMatrix:
    """Bitset → CSR with the bitset as each of ``n_repeat`` identical rows.
    (ref: convert/detail/bitset_to_csr.cuh — the bitset is broadcast as
    repeated rows of the output.)"""
    bits = np.asarray(bitset.to_dense())
    (cols,) = np.nonzero(bits)
    nnz_row = len(cols)
    indptr = np.arange(n_repeat + 1, dtype=np.int32) * nnz_row
    all_cols = np.tile(cols.astype(np.int32), n_repeat)
    return CSRMatrix(jnp.asarray(indptr), jnp.asarray(all_cols),
                     jnp.ones((nnz_row * n_repeat,), jnp.float32),
                     (n_repeat, bitset.n_bits))
