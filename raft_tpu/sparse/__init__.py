"""raft_tpu.sparse — sparse formats, linalg, solvers. (ref:
cpp/include/raft/sparse, SURVEY §2.5.)"""

from raft_tpu.core.sparse_types import COOMatrix, COOStructure, CSRMatrix, CSRStructure
from raft_tpu.sparse import convert
from raft_tpu.sparse import linalg
from raft_tpu.sparse import matrix
from raft_tpu.sparse import op
from raft_tpu.sparse import solver
from raft_tpu.sparse.linalg import prepare_sddmm, prepare_spmv
from raft_tpu.sparse.sharded import (ShardedTiledELL, shard_spmv_operand,
                                     spmm_sharded, spmv_sharded)
from raft_tpu.sparse.tiled import TiledELL, TiledPairs, TiledPairsSpmv

__all__ = [
    "COOMatrix", "COOStructure", "CSRMatrix", "CSRStructure", "TiledELL", "TiledPairsSpmv",
    "TiledPairs", "ShardedTiledELL", "convert", "linalg", "matrix", "op",
    "prepare_sddmm", "prepare_spmv", "shard_spmv_operand", "solver",
    "spmm_sharded", "spmv_sharded",
]
