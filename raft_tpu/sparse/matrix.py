"""Sparse matrix ops: CSR select_k, diagonal, tf-idf / BM25 preprocessing.

(ref: cpp/include/raft/sparse/matrix/select_k.cuh +
detail/select_k-inl.cuh (221), matrix/detail/diagonal.cuh (255),
matrix/preprocessing.cuh:28,63,101,167 encode_tfidf/encode_bm25 with impl
sparse/matrix/detail/preprocessing.cuh.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.linalg import _as_coo_parts, diagonal as _diagonal


def select_k(res, csr: CSRMatrix, k: int, select_min: bool = True,
             fill_value=None) -> Tuple[jax.Array, jax.Array]:
    """Top-k per CSR row → dense (values [n_rows,k], indices [n_rows,k]).

    Rows with fewer than k nonzeros are padded with ``fill_value`` (±inf by
    default) and index −1, matching the reference's semantics.
    (ref: sparse/matrix/detail/select_k-inl.cuh)

    TPU-first: instead of per-row heaps, one global stable sort of
    (row, value) pairs ranks every nonzero within its row — O(nnz log nnz)
    fully on the sort unit — then a scatter places rank<k survivors.
    """
    rows, cols, vals, shape = _as_coo_parts(csr)
    n_rows = shape[0]
    expects(k > 0, "select_k: k must be positive")
    if fill_value is None:
        fill_value = jnp.inf if select_min else -jnp.inf

    sort_vals = vals if select_min else -vals
    # order within each row by value (stable on row then value)
    order = jnp.lexsort((sort_vals, rows))
    s_rows = rows[order]
    s_cols = cols[order]
    s_vals = vals[order]
    # rank of each sorted entry within its row = position - row_start
    indptr = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(jnp.bincount(rows, length=n_rows)).astype(jnp.int32),
    ])
    pos = jnp.arange(s_rows.shape[0], dtype=jnp.int32)
    rank = pos - indptr[s_rows]
    out_v = jnp.full((n_rows, k), fill_value, vals.dtype)
    out_i = jnp.full((n_rows, k), -1, jnp.int32)
    # rank >= k scatters out of bounds on axis 1 → dropped by mode="drop"
    out_v = out_v.at[s_rows, rank].set(s_vals, mode="drop")
    out_i = out_i.at[s_rows, rank].set(s_cols.astype(jnp.int32), mode="drop")
    return out_v, out_i


def diagonal(res, A) -> jax.Array:
    """Extract the main diagonal. (ref: sparse/matrix/detail/diagonal.cuh;
    delegates to the single implementation in sparse.linalg.)"""
    return _diagonal(res, A)


def set_diagonal(res, A, diag):
    """Overwrite existing diagonal entries with ``diag[row]`` (entries must
    already exist in the structure, as in the reference's in-place kernel).
    (ref: matrix/detail/diagonal.cuh ``set_diagonal``)"""
    rows, cols, vals, _ = _as_coo_parts(A)
    diag = jnp.asarray(diag)
    on = rows == cols
    return A.with_values(jnp.where(on, diag[rows], vals))


def scale_by_diagonal_symmetric(res, A, diag) -> "CSRMatrix | COOMatrix":
    """A_ij ← A_ij · d_i · d_j (the D A D scaling used by the normalized
    Laplacian). (ref: matrix/detail/diagonal.cuh scaling helpers)"""
    rows, cols, vals, _ = _as_coo_parts(A)
    diag = jnp.asarray(diag)
    return A.with_values(vals * diag[rows] * diag[cols])


# ---- tf-idf / BM25 (ref: sparse/matrix/preprocessing.cuh) ----
def _feature_doc_counts(cols, n_cols):
    """Occurrences per feature (histogram of column ids).
    (ref: detail/preprocessing.cuh ``fit_tfidf`` — stats::histogram)"""
    return jnp.bincount(cols, length=n_cols)


def encode_tfidf(res, A):
    """TF-IDF re-weighting of a term-frequency matrix.

    Per the reference formula (detail/preprocessing.cuh ``transform_tfidf``):
    tf = log(value), idf = log(num_rows / feature_count[col] + 1),
    out = tf · idf.
    (ref: sparse/matrix/preprocessing.cuh:28 (COO), :63 (CSR))
    """
    rows, cols, vals, shape = _as_coo_parts(A)
    feat_count = _feature_doc_counts(cols, shape[1]).astype(vals.dtype)
    safe = jnp.where(feat_count > 0, feat_count, jnp.ones_like(feat_count))
    tf = jnp.log(vals)
    idf = jnp.log(shape[0] / safe[cols] + 1.0)
    return A.with_values(tf * idf)


def encode_bm25(res, A, k_param: float = 1.6, b_param: float = 0.75):
    """Okapi BM25 re-weighting.

    Per the reference formula (detail/preprocessing.cuh ``transform_bm25``):
    tf = log(value); idf = log(num_rows/feature_count[col] + 1);
    bm = (k+1)·tf / (k·((1−b) + b·row_len[row]/avg_len) + tf);
    out = idf · bm, with row_len = per-row sum of values and
    avg_len = total/num_rows.
    (ref: sparse/matrix/preprocessing.cuh:101 (COO), :167 (CSR))
    """
    rows, cols, vals, shape = _as_coo_parts(A)
    feat_count = _feature_doc_counts(cols, shape[1]).astype(vals.dtype)
    safe = jnp.where(feat_count > 0, feat_count, jnp.ones_like(feat_count))
    row_len = jax.ops.segment_sum(vals, rows, num_segments=shape[0])
    full_len = jnp.sum(vals)
    avg_len = full_len / shape[0]
    tf = jnp.log(vals)
    idf = jnp.log(shape[0] / safe[cols] + 1.0)
    bm = ((k_param + 1.0) * tf) / (
        k_param * ((1.0 - b_param) + b_param * (row_len[rows] / avg_len)) + tf)
    return A.with_values(idf * bm)
