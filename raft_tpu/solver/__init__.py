"""raft_tpu.solver — linear assignment. (ref: cpp/include/raft/solver,
SURVEY §2.7.)"""

from raft_tpu.solver.linear_assignment import (
    LinearAssignmentProblem,
    solve_lap,
)

__all__ = ["LinearAssignmentProblem", "solve_lap"]
