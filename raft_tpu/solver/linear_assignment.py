"""Linear assignment problem (LAP).

(ref: cpp/include/raft/solver/linear_assignment.cuh:60 ``class
LinearAssignmentProblem``, ``solve()`` at :125 — batched GPU Hungarian
(Date–Nagi), kernels solver/detail/lap_kernels.cuh, routines
lap_functions.cuh, types linear_assignment_types.hpp.)

TPU re-design: the Date–Nagi Hungarian alternates fine-grained frontier
kernels — a poor fit for SPMD vectors. The auction algorithm (Bertsekas)
is the parallel-native equivalent: every unassigned row bids
simultaneously (vector max/segment ops), columns resolve winners in one
scatter, ε-scaling drives the duality gap down. Batched like the
reference via ``vmap``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


@partial(jax.jit, static_argnames=("n",))
def _auction_solve(cost, n: int):
    """Min-cost assignment via auction with ε-scaling.
    Returns row→col assignment [n] (int32)."""
    value = -cost.astype(jnp.float32)  # auction maximizes value
    big = jnp.asarray(1e30, jnp.float32)
    rows = jnp.arange(n, dtype=jnp.int32)
    cols = jnp.arange(n, dtype=jnp.int32)

    # per-stage iteration cap: auction theory bounds warm-started stages
    # well under this; the cap exists so degenerate float ties can never
    # hang the solver — an early-exited stage just leaves slack that the
    # certificate (below) reports honestly
    max_iters = 60 * n + 2000

    def stage(prices, eps):
        col_of = jnp.full((n,), -1, jnp.int32)  # row -> col
        row_of = jnp.full((n,), -1, jnp.int32)  # col -> row

        def cond(state):
            return jnp.any(state[1] < 0) & (state[3] < max_iters)

        def body(state):
            prices, col_of, row_of, it = state
            unassigned = col_of < 0
            net = value - prices[None, :]
            best_col = jnp.argmax(net, axis=1).astype(jnp.int32)
            v1 = jnp.max(net, axis=1)
            net2 = net.at[rows, best_col].set(-big)
            v2 = jnp.max(net2, axis=1)
            bid = prices[best_col] + (v1 - v2) + eps
            seg = jnp.where(unassigned, best_col, n)  # dummy seg for idle rows
            col_best = jax.ops.segment_max(
                jnp.where(unassigned, bid, -big), seg, num_segments=n + 1)[:n]
            at_max = unassigned & (bid >= col_best[best_col])
            winner = jax.ops.segment_min(
                jnp.where(at_max, rows, n), seg, num_segments=n + 1)[:n]
            has_w = winner < n
            # evict previous owners of won columns
            evict_rows = jnp.where(has_w & (row_of >= 0), row_of, n)
            col_of = col_of.at[evict_rows].set(-1, mode="drop")
            # assign winners
            win_rows = jnp.where(has_w, winner, n)
            col_of = col_of.at[win_rows].set(cols, mode="drop")
            row_of = jnp.where(has_w, winner, row_of)
            prices = jnp.where(has_w, col_best, prices)
            return prices, col_of, row_of, it + 1

        prices, col_of, row_of, _ = jax.lax.while_loop(
            cond, body, (prices, col_of, row_of, jnp.int32(0)))
        # a capped-out stage may leave rows unassigned: give them the
        # leftover columns (any perfect matching completion) so later
        # stages / the certificate always see a complete assignment
        unassigned_row = col_of < 0
        free_col = row_of < 0
        rank_r = jnp.cumsum(unassigned_row.astype(jnp.int32)) - 1
        free_ids = jnp.nonzero(free_col, size=n, fill_value=0)[0].astype(
            jnp.int32)
        col_of = jnp.where(unassigned_row, free_ids[rank_r], col_of)
        return prices, col_of

    # ε-scaling: final ε bounds the objective error by n·ε. 1/(n+1) makes
    # integer costs exact. ε is FLOORED at ~the f32 ulp of the price scale
    # (max_abs·2⁻²⁰): below that, bids no longer change prices and the
    # auction ping-pongs instead of converging — refinement past float
    # resolution is meaningless, and the certificate below reports the
    # true residual instead.
    max_abs = jnp.maximum(jnp.max(jnp.abs(value)), 1e-12)
    eps_floor = max_abs * (2.0 ** -20)
    n_stages = 12
    eps_list = [jnp.maximum(max_abs / (4.0 ** i), eps_floor)
                for i in range(1, n_stages)]
    eps_list.append(jnp.maximum(
        jnp.minimum(1.0 / (n + 1), max_abs / (4.0 ** n_stages)), eps_floor))

    def scan_body(prices, eps):
        prices, col_of = stage(prices, eps)
        return prices, col_of

    prices, col_assignments = jax.lax.scan(
        scan_body, jnp.zeros((n,), jnp.float32), jnp.asarray(eps_list))
    assign = col_assignments[-1]

    # exact 2-swap refinement: ε-floor ties leave the auction a few
    # sub-resolution swaps short of optimal; each sweep applies every
    # mutually-best IMPROVING pair swap (delta < 0) in parallel. The
    # duality-gap bound below holds for ANY assignment under the final
    # prices, and each applied swap shrinks it by exactly the swap's
    # improvement — refinement can only tighten the certificate.
    cost_f = cost.astype(jnp.float32)

    def sweep(a, _):
        cii = jnp.take_along_axis(cost_f, a[:, None], axis=1)[:, 0]
        cij = cost_f[:, a]                     # cij[i, j] = cost[i, a_j]
        delta = cij + cij.T - cii[:, None] - cii[None, :]
        delta = delta + jnp.where(jnp.eye(n, dtype=bool), big, 0.0)
        bestj = jnp.argmin(delta, axis=1).astype(jnp.int32)
        bestd = jnp.min(delta, axis=1)
        ok = (bestd < 0) & (rows < bestj) & (bestj[bestj] == rows)
        a_new = jnp.where(ok, a[bestj], a)
        a_new = a_new.at[jnp.where(ok, bestj, n)].set(a[rows], mode="drop")
        return a_new, None

    assign, _ = jax.lax.scan(sweep, assign, None, length=6)

    # certificate: with final prices p, per-row slack
    #   σ_i = max_k (value[i,k] − p[k]) − (value[i,aᵢ] − p[aᵢ]) ≥ 0,
    # and Σσ bounds the objective gap to the optimum (LP duality /
    # complementary slackness). Σσ == 0 ⟹ the assignment is PROVABLY
    # optimal — the exactness check the reference's Hungarian gets
    # structurally (ref: linear_assignment.cuh:60,125).
    net = value - prices[None, :]
    slack = jnp.max(net, axis=1) - net[rows, assign]
    return assign, jnp.sum(jnp.maximum(slack, 0.0))


# largest n the exact Jonker–Volgenant tail accepts: n sequential
# augmentations of O(n) while-loop steps — fine as a small-n tail,
# wrong as the primary path at reference scale (the auction is that)
_EXACT_TAIL_MAX_N = 8192


@partial(jax.jit, static_argnames=("n",))
def _jv_solve(cost, n: int):
    """Exact min-cost assignment via Jonker–Volgenant shortest
    augmenting paths (dense, the algorithm scipy's
    ``linear_sum_assignment`` implements). Sequential by nature — n
    augmentations, each an O(n)-step Dijkstra over columns with O(n)
    vector work per step — so it serves as the EXACT-REFINEMENT TAIL
    for small n behind the auction solver, closing the contract gap
    with the reference's exact Hungarian (linear_assignment.cuh:125).

    Returns (row→col assignment [n], row duals u [n]): the certificate
    itself — project the duals to feasibility (v_j ← min_i cost[i,j]−u_i),
    then LP duality bounds ``objective − optimum ≤ obj − Σu − Σv`` (0 in
    exact arithmetic) — is NOT computed here: the ENFORCED tol contract
    recomputes it in float64 on the host-synced duals/assignment (see
    :func:`_certify_f64`), because an in-graph f32 reduction of obj/Σu/Σv
    can round a positive gap down to below tol and under-report it."""
    INF = jnp.float32(3e38)
    cost = cost.astype(jnp.float32)
    virt = jnp.int32(n)  # virtual start column (the e-maxx "column 0")

    def augment(carry, i0):
        u, v, p = carry          # p: col → row over [n+1]; p[virt] = i0
        p = p.at[n].set(i0)
        minv = jnp.full((n,), INF, jnp.float32)
        way = jnp.full((n,), virt, jnp.int32)
        used = jnp.zeros((n + 1,), bool)

        def cond(s):
            u, v, p, mw, used, j0 = s
            return p[j0] >= 0      # stop on reaching a free column

        def body(s):
            u, v, p, (minv, way), used, j0 = s
            used = used.at[j0].set(True)
            i_row = p[j0]
            cur = cost[i_row] - u[i_row] - v       # [n]
            better = (cur < minv) & ~used[:n]
            minv = jnp.where(better, cur, minv)
            way = jnp.where(better, j0, way)
            masked = jnp.where(used[:n], INF, minv)
            j1 = jnp.argmin(masked).astype(jnp.int32)
            delta = masked[j1]
            # dual step: visited columns' rows gain delta (incl. i0 via
            # the virtual column — i0 is unmatched, so no double-add),
            # visited column prices drop, free columns' labels shrink
            u = u.at[jnp.where(used[:n], p[:n], n)].add(delta,
                                                        mode="drop")
            u = u.at[p[n]].add(delta)
            v = jnp.where(used[:n], v - delta, v)
            minv = jnp.where(used[:n], minv, minv - delta)
            return u, v, p, (minv, way), used, j1

        u, v, p, (minv, way), used, j0 = jax.lax.while_loop(
            cond, body, (u, v, p, (minv, way), used, virt))

        # backtrack the augmenting path: p[j0] ← p[way[j0]] until the
        # virtual column is reached
        def bt_body(s):
            j0, p = s
            j1 = way[j0]
            p = p.at[j0].set(p[j1])
            return j1, p

        _, p = jax.lax.while_loop(lambda s: s[0] != virt, bt_body,
                                  (j0, p))
        return (u, v, p), None

    u0 = jnp.zeros((n,), jnp.float32)
    v0 = jnp.zeros((n,), jnp.float32)
    p0 = jnp.full((n + 1,), -1, jnp.int32)
    (u, v, p), _ = jax.lax.scan(augment, (u0, v0, p0),
                                jnp.arange(n, dtype=jnp.int32))
    row_of = p[:n]                               # col → row
    assign = jnp.zeros((n,), jnp.int32).at[row_of].set(
        jnp.arange(n, dtype=jnp.int32))          # row → col

    return assign, u


def _certify_f64(cost_np: np.ndarray, assign_np: np.ndarray,
                 u_np: np.ndarray) -> np.ndarray:
    """ENFORCED optimality-gap certificate, float64 on the host.

    For each batched instance: project the row duals to feasibility
    (v_j = min_i cost[i,j] − u_i), then LP duality proves
    ``objective − optimum ≤ obj − Σu − Σv_feas``. All three terms
    (objective, Σu, Σv_feas) are evaluated in float64 via numpy on the
    host-synced duals/assignment, so f32 reduction rounding cannot
    under-report the gap a tol check then trusts — the dual VALUES still
    carry f32 solver noise, but duality makes the bound valid for ANY
    duals; only the arithmetic that sums them must not round down.
    Inputs: cost [b, n, n], assign [b, n] (row→col), u [b, n]."""
    cost64 = np.asarray(cost_np, np.float64)
    u64 = np.asarray(u_np, np.float64)
    a = np.asarray(assign_np, np.int64)
    v_feas = (cost64 - u64[:, :, None]).min(axis=1)          # [b, n]
    obj = np.take_along_axis(cost64, a[:, :, None], axis=2)[:, :, 0].sum(
        axis=1)
    return np.maximum(obj - (u64.sum(axis=1) + v_feas.sum(axis=1)), 0.0)


class LinearAssignmentProblem:
    """(ref: solver/linear_assignment.cuh:60)"""

    def __init__(self, res, size: int, batchsize: int = 1):
        self.res = res
        self.size = int(size)
        self.batchsize = int(batchsize)
        self._row_assignments = None
        self._obj = None
        self._gap_bound = None

    def solve(self, cost, tol: float = None) -> Tuple[jnp.ndarray,
                                                      jnp.ndarray]:
        """Solve min-cost assignment. cost: [n,n] or [batch,n,n].
        Returns (row_assignments, objective). (ref: :125 ``solve``)

        ``tol`` is the solver's accuracy contract — a proven absolute
        bound on ``objective − optimum`` the result must satisfy:

        - ``tol=None`` (default): accept the auction solution with its
          certificate (≤ n·max|cost|·2⁻²⁰; in practice it matches the
          exact Hungarian on generic float costs — tested vs scipy).
        - ``tol=x`` (incl. ``0.0``): instances whose auction
          certificate exceeds x are re-solved with the exact
          Jonker–Volgenant tail (n ≤ 8192) — the contract the
          reference's exact Hungarian states
          (linear_assignment.cuh:125). ``tol`` is ENFORCED: if the
          final certified gap still exceeds it (n > 8192, or a tol
          below f32 dual resolution ~n·max|cost|·2⁻²⁴ on float costs),
          ValueError is raised rather than returning a non-conforming
          answer. Integer-valued costs typically certify exactly 0.0;
          for float costs prefer a small positive tol.

        Every solve carries a post-solve optimality certificate:
        ``get_optimality_gap_bound()`` returns a proven upper bound on
        ``objective − optimum`` (complementary-slackness slack sum),
        0.0 when the result is provably optimal. Integer costs are
        solved exactly by the auction alone when
        ``max|cost| ≤ ~2²⁰/(n+1)`` — beyond that, ε < 1/(n+1) is below
        f32 price resolution; the exact tail covers the rest.
        """
        cost = jnp.asarray(cost)
        single = cost.ndim == 2
        if single:
            cost = cost[None]
        expects(cost.shape[1] == cost.shape[2] == self.size,
                "LAP: cost must be [batch, %d, %d]", self.size, self.size)
        assign, gap = jax.vmap(lambda c: _auction_solve(c, self.size))(cost)
        if tol is not None:
            need = np.asarray(gap) > tol
            if bool(need.any()):
                if self.size > _EXACT_TAIL_MAX_N:
                    raise ValueError(
                        f"LAP: auction certificate "
                        f"{float(np.asarray(gap).max()):.3g} exceeds "
                        f"tol={tol:g} and n={self.size} is beyond the "
                        f"exact tail's envelope ({_EXACT_TAIL_MAX_N}); "
                        "loosen tol or reduce n")
                # re-solve ONLY the instances that missed the contract
                idx = np.flatnonzero(need)
                assign_x, u_x = jax.vmap(
                    lambda c: _jv_solve(c, self.size))(cost[idx])
                assign = assign.at[idx].set(assign_x)
                # ENFORCED certificate: recomputed in float64 on the
                # host-synced duals/assignment — an in-graph f32
                # reduction could round a >tol gap below tol
                gap_x = _certify_f64(np.asarray(cost[idx]),
                                     np.asarray(assign_x),
                                     np.asarray(u_x))
                gap = gap.at[idx].set(
                    jnp.asarray(gap_x, jnp.float32))
                worst = float(max(gap_x.max(initial=0.0),
                                  float(np.asarray(gap).max())))
                if worst > tol:
                    raise ValueError(
                        f"LAP: certified gap {worst:.3g} exceeds "
                        f"tol={tol:g} even after the exact tail — the "
                        f"certificate is bounded below by f32 dual "
                        f"resolution (~n·max|cost|·2⁻²⁴ for float "
                        "costs); loosen tol")
        obj = jnp.take_along_axis(cost, assign[:, :, None], axis=2)[:, :, 0].sum(axis=1)
        self._row_assignments = assign[0] if single else assign
        self._obj = obj[0] if single else obj
        self._gap_bound = gap[0] if single else gap
        return self._row_assignments, self._obj

    def get_assignments(self):
        return self._row_assignments

    def get_objective(self):
        return self._obj

    def get_optimality_gap_bound(self):
        """Proven upper bound on ``objective − optimum`` for the last
        solve (0.0 ⟹ provably optimal). See :meth:`solve`."""
        return self._gap_bound


@instrument("solver.solve_lap")
def solve_lap(res, cost, tol: float = None):
    """Functional convenience wrapper. See
    :meth:`LinearAssignmentProblem.solve` for the ``tol`` contract."""
    fault_point("solve_lap")
    cost = jnp.asarray(cost)
    n = cost.shape[-1]
    lap = LinearAssignmentProblem(res, n)
    return lap.solve(cost, tol=tol)
