#!/usr/bin/env python
"""Static check: every hot-path primitive carries @instrument, and the
cost-capture sites feed the roofline profiler.

Pure-AST, no TPU (and no raft_tpu import) needed, so it runs anywhere —
it is wired into the tier-1 suite via tests/test_observability.py. The
check asserts:

1. per module in :data:`HOT_PATHS`: the module imports ``instrument``
   from ``raft_tpu.observability``, and each listed function is
   decorated with it (bare ``@instrument`` or ``@instrument(...)``,
   plain name or attribute spelling);
2. per module in :data:`COST_CAPTURE_SITES`: the module calls the named
   profiler capture method — the static guarantee that everything the
   hot-path list reports (AOT runtime entries via ``_aot_call``,
   benchmark measurements via ``Fixture.run``) also flows through XLA
   cost capture, so ``roofline_report()`` can attribute it. Removing a
   capture call silently reverts BENCH artifacts to seconds-only — the
   exact evidence regression this gate exists to catch.

Extend HOT_PATHS when a new primitive ships — forgetting to is exactly
the regression this check exists to catch: a hot path that silently
ships unobserved.

Since ISSUE 13, the MIRROR tables (FAULT_SITES, EMITTER_KINDS) are no
longer hand-pinned: they are DERIVED from source by
``raft_tpu.analysis.registry`` (graftlint's registry pass) and imported
here, so this tool and graftlint can never disagree about what a
"site" is — equality is pinned by tests/test_analysis.py. The curated
tables that remain (HOT_PATHS, COST_CAPTURE_SITES, EVENT_SITES,
QUALITY_SITES, KERNEL_VARIANTS) are *policy* — what MUST be covered —
and graftlint diffs them against the derived ground truth in the
reverse direction (an @instrument function missing from HOT_PATHS is
a lint error).

Usage: ``python tools/check_instrumented.py`` (exit 0 = clean).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

try:                      # imported as tools.check_instrumented
    from tools.graftlint import load_analysis
except ImportError:       # imported with tools/ on sys.path
    from graftlint import load_analysis

# module (repo-relative) → functions that must be instrumented
HOT_PATHS: Dict[str, Sequence[str]] = {
    "raft_tpu/matrix/select_k.py": ("select_k",),
    "raft_tpu/matrix/select_k_chunked.py": ("select_k_chunked",),
    "raft_tpu/matrix/select_k_slotted.py": ("select_k_slotted",),
    "raft_tpu/distance/pairwise.py": ("pairwise_distance",),
    "raft_tpu/distance/fused_l2nn.py": (
        "fused_l2_nn_argmin", "knn", "knn_sharded"),
    "raft_tpu/distance/knn_fused.py": ("knn_fused",
                                       "prepare_knn_index"),
    "raft_tpu/sparse/tiled.py": ("tile_csr", "tile_csr_pairs"),
    "raft_tpu/sparse/sharded.py": ("spmv_sharded", "spmm_sharded"),
    "raft_tpu/solver/linear_assignment.py": ("solve_lap",),
    "raft_tpu/tune/fused.py": ("autotune_fused",),
    "raft_tpu/tune/sharded.py": ("autotune_sharded",),
    "raft_tpu/tune/ivf.py": ("autotune_fine_scan",
                             "autotune_pq_scan"),
    "raft_tpu/distance/knn_sharded.py": ("knn_fused_sharded",),
    "raft_tpu/serving/engine.py": ("execute_batch",),
    "raft_tpu/serving/snapshot.py": ("build_snapshot",),
    "raft_tpu/cluster/kmeans.py": ("kmeans_fit", "kmeans_predict"),
    "raft_tpu/ann/ivf_flat.py": ("build_ivf_flat", "search_ivf_flat"),
    "raft_tpu/ann/ivf_pq.py": ("build_ivf_pq", "search_ivf_pq"),
    "raft_tpu/mutable/index.py": ("apply_upsert", "apply_delete",
                                  "search_view"),
}

# module (repo-relative) → profiler capture methods it must call
# (attribute calls, e.g. ``res.profiler.capture(...)``)
COST_CAPTURE_SITES: Dict[str, Sequence[str]] = {
    "raft_tpu/runtime/entry_points.py": ("capture",),
    "raft_tpu/benchmark.py": ("capture_fn",),
    "raft_tpu/tune/fused.py": ("capture_fn",),
    "raft_tpu/tune/sharded.py": ("capture_fn",),
    # the ANN tier's hot kernels: the k-means assignment tile and the
    # IVF fine scan both feed the roofline profiler, so BENCH_ANN
    # frontiers carry flops/bytes next to recall
    "raft_tpu/cluster/kmeans.py": ("capture_fn",),
    "raft_tpu/ann/ivf_flat.py": ("capture_fn",),
    # the PQ ADC table build — the per-chunk cost the compressed tier
    # adds on top of the shared fine-scan machinery
    "raft_tpu/ann/ivf_pq.py": ("capture_fn",),
    # the int8 quantize prep (prepare_knn_index db_dtype="int8")
    "raft_tpu/distance/knn_fused.py": ("capture_fn",),
}

# sharded-merge observability sites: the merge rounds must flow through
# the COUNTED comms surface (MeshComms methods that call _count), and
# comms.py must count the p2p/permute collectives under their own
# labels. A merge round rewritten onto raw jax.lax collectives would
# silently vanish from the metrics exporters — exactly the regression
# this table catches.
# module → attribute-call names it must contain
SHARDED_MERGE_SITES: Dict[str, Sequence[str]] = {
    "raft_tpu/distance/knn_sharded.py": ("collective_permute",
                                         "allgather"),
}
# comms.py must register these collective labels with _count(...)
COUNTED_COLLECTIVES = ("collective_permute", "device_send")

# module (repo-relative) → fault-injection sites it carries. DERIVED
# from source (every literal ``fault_point("<site>")`` call) by
# graftlint's registry derivation — this tool IMPORTS the ground truth
# instead of redeclaring it, so the two can never disagree about what
# a site is. The policy checks on top: every HOT_PATHS module must
# carry ≥ 1 site (check_fault_sites) and the derived site names must
# agree with faults.KNOWN_SITES in BOTH directions
# (check_fault_registry; also pinned at runtime by
# tests/test_resilience.py).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DERIVED = load_analysis(_REPO_ROOT).registry.derive_registries(
    _REPO_ROOT)

FAULT_SITES: Dict[str, Sequence[str]] = dict(_DERIVED.fault_sites)

# timeline-event gate: every hot-path module and every fault-site
# module must emit flight-recorder events — a hot path invisible in a
# Perfetto trace cannot be reconstructed post-mortem, which is exactly
# the regression this gate catches. A module "emits" by referencing at
# least the listed emitter callables (``@instrument``/``fault_point``
# route through the flight recorder; the ``emit_*`` helpers live in
# raft_tpu/observability/timeline.py). EMITTER_KINDS maps each emitter
# to the flight event kind it produces; the checker statically asserts
# every kind exists in flight.KNOWN_EVENT_KINDS (parsed from the
# source), and tests/test_flight.py pins the same fact at runtime.
# emitter → flight event kind. DERIVED: every ``emit_*``/``record_*``
# helper in observability/timeline.py paired with the literal kind its
# body records, plus analysis.registry.ALIAS_EMITTERS (the bridges —
# @instrument → span, fault_point → fault, quality recorders →
# quality — whose kind cannot be read off a timeline literal).
EMITTER_KINDS: Dict[str, str] = dict(_DERIVED.emitter_kinds)

EVENT_SITES: Dict[str, Sequence[str]] = {
    # every HOT_PATHS module: spans via @instrument + fault events
    **{rel: ("instrument", "fault_point") for rel in HOT_PATHS},
    # fault-site modules outside HOT_PATHS
    "raft_tpu/runtime/entry_points.py": (
        "fault_point", "emit_compile", "emit_dispatch"),
    "raft_tpu/sparse/plan_cache.py": ("fault_point",),
    "raft_tpu/comms/host_comms.py": ("fault_point",),
    # the emit wiring itself — deleting a bridge silently empties the
    # timeline even though every call site still "emits"
    "raft_tpu/comms/comms.py": ("record_collective",),
    "raft_tpu/resilience/faults.py": ("emit_fault",),
    "raft_tpu/resilience/policy.py": ("emit_retry",
                                      "emit_degradation"),
    "raft_tpu/resilience/deadline.py": ("emit_deadline",),
    "raft_tpu/core/interruptible.py": ("emit_deadline",),
    "raft_tpu/observability/spans.py": ("emit_span",),
    "raft_tpu/observability/hooks.py": ("emit_collective",
                                        "emit_compile",
                                        "emit_benchmark"),
    "raft_tpu/benchmark.py": ("record_drift",),
    # the serving engine: every module under raft_tpu/serving/ must
    # appear here (enforced structurally by check_serving_coverage) —
    # enqueue/flush/shed/swap/warmup all flow through emit_serving
    "raft_tpu/serving/engine.py": ("instrument", "fault_point",
                                   "emit_serving", "emit_flow"),
    "raft_tpu/serving/snapshot.py": ("instrument", "fault_point",
                                     "emit_serving"),
    "raft_tpu/serving/buckets.py": ("emit_marker",),
    # the ANN tier: per-iteration k-means markers, IVF build/search
    # markers (probed-bytes fraction rides the search event)
    "raft_tpu/cluster/kmeans.py": ("instrument", "fault_point",
                                   "emit_marker"),
    "raft_tpu/ann/ivf_flat.py": ("instrument", "fault_point",
                                 "emit_marker"),
    # the compressed tier: build/search markers (eq stats, schedule
    # picks, certificate fallbacks) ride next to the span/fault events
    "raft_tpu/ann/ivf_pq.py": ("instrument", "fault_point",
                               "emit_marker"),
    # the fine-scan/pq schedule autotuner (schema 5/6 columns)
    "raft_tpu/tune/ivf.py": ("instrument", "fault_point"),
    # the quantized index build: the quantize_index marker (per-build
    # Eq stats) rides next to the span + fault events
    "raft_tpu/distance/knn_fused.py": ("instrument", "fault_point",
                                       "emit_marker", "record_pending"),
    # the quality plane itself: its recorders must still route through
    # the flight emitter (deleting the bridge would silently empty the
    # quality timeline while every call site keeps "recording")
    "raft_tpu/observability/quality.py": ("emit_quality",),
    # the mutation plane: every write emits into the write-ahead
    # mutation stream, the layout prep marks its geometry, and the
    # delta-tail searches report certificate/fixup counters like every
    # other certified path
    "raft_tpu/mutable/index.py": ("instrument", "fault_point",
                                  "emit_mutation", "record_pending"),
    "raft_tpu/mutable/layout.py": ("emit_marker",),
    # the durability plane: WAL segment lifecycle rides markers,
    # checkpoint commits + recoveries ride the mutation stream — a
    # crash recovery invisible in the flight timeline cannot be
    # audited post-mortem
    "raft_tpu/mutable/wal.py": ("fault_point", "emit_marker"),
    "raft_tpu/mutable/checkpoint.py": ("fault_point", "emit_mutation"),
    # the telemetry front door (ISSUE 16): explain records land on the
    # flight timeline as "explain" events, SLO burn transitions as
    # "alert" events — deleting either bridge silently blinds the
    # debugz surfaces while every capture/tick keeps "running"
    "raft_tpu/observability/explain.py": ("emit_explain",),
    "raft_tpu/observability/slo.py": ("emit_alert",),
    # the forensics plane (ISSUE 17): the watchdog's stall detections
    # and the blackbox's clean-shutdown epilogue are themselves flight
    # events — a hang or a shutdown invisible in the timeline would
    # defeat the very postmortem this plane exists to serve
    "raft_tpu/observability/watchdog.py": ("emit_stall",),
    "raft_tpu/observability/blackbox.py": ("emit_epilogue",),
}

#: quality-telemetry gate (ISSUE 10): every module with a certificate /
#: fixup / rescore path must report into the quality plane — a
#: certified result path that silently stops counting its fixups is
#: exactly the evidence regression ROADMAP item 2 cannot afford (the
#: measured TPU fixup rate decides per-query Eq tightening). Each
#: module must reference the listed observability.quality recorders.
QUALITY_SITES: Dict[str, Sequence[str]] = {
    "raft_tpu/distance/knn_fused.py": ("record_pending",),
    "raft_tpu/distance/knn_sharded.py": ("record_pending",),
    "raft_tpu/ann/ivf_flat.py": ("record_certificate",
                                 "record_pending"),
    # the PQ tier's ADC scan reports its certificate/rerun counters
    # at the host sync its rerun decision already pays, plus the
    # per-rung ladder outcomes (certified / widened / exact_rerun)
    "raft_tpu/ann/ivf_pq.py": ("record_certificate",
                               "record_pq_rungs"),
    "raft_tpu/runtime/entry_points.py": ("record_pending",),
    # the serving engine's quality surface is the shadow sampler
    "raft_tpu/serving/engine.py": ("ShadowSampler",),
    # the mutable planes: base and delta-tail searches both report
    # certificate/fixup counters (the delta tail is a certified path
    # like any other — ISSUE 11)
    "raft_tpu/mutable/index.py": ("record_pending",),
}

_FLIGHT_MODULE = "raft_tpu/observability/flight.py"

# defining module → (kernel-variant entry points, consuming module):
# the grid-order variants must EXIST where the footprint model and the
# autotuner expect them, and the consumer must actually reference them
# — deleting a variant (or silently unrouting it) would leave tuned
# tables naming a kernel production can't run.
KERNEL_VARIANTS: Dict[str, Tuple[Sequence[str], str]] = {
    "raft_tpu/ops/fused_l2_topk_pallas.py": (
        ("fused_l2_group_topk_packed",
         "fused_l2_group_topk_packed_db",
         "fused_l2_group_topk_packed_dbuf",
         "fused_l2_group_topk_packed_db_q8",
         "fused_l2_group_topk_packed_dbuf_q8"),
        "raft_tpu/distance/knn_fused.py"),
    # the list-major IVF fine-scan family (ISSUE 14): stream each
    # probed list once for all queries probing it; consumed by the
    # ann tier's resolve_fine_scan "list" schedule
    "raft_tpu/ops/fine_scan_pallas.py": (
        ("fine_scan_list_major",
         "fine_scan_list_major_q8"),
        "raft_tpu/ann/ivf_flat.py"),
    # the IVF-PQ ADC kernel (ISSUE 15): the codes slab streamed
    # through the list-major schedule against the VMEM-resident
    # lookup table; consumed by the ann.ivf_pq "pq" schedule
    "raft_tpu/ops/pq_scan_pallas.py": (
        ("pq_scan_list_major",),
        "raft_tpu/ann/ivf_pq.py"),
}

def _decorator_is_instrument(dec: ast.expr) -> bool:
    """True for @instrument, @instrument(...), @observability.instrument,
    and @raft_tpu.observability.instrument(...)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Attribute):
        return dec.attr == "instrument"
    return isinstance(dec, ast.Name) and dec.id == "instrument"


def _imports_instrument(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if (node.module or "").startswith("raft_tpu.observability"):
                if any(a.name == "instrument" for a in node.names):
                    return True
        elif isinstance(node, ast.Import):
            if any(a.name.startswith("raft_tpu.observability")
                   for a in node.names):
                return True
    return False


def _calls_attribute(tree: ast.Module, attr: str) -> bool:
    """True when the module contains a call ``<expr>.<attr>(...)``."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == attr):
            return True
    return False


def check_cost_capture(root: str = _REPO_ROOT,
                       sites: Dict[str, Sequence[str]] = None) -> List[str]:
    """Violations for :data:`COST_CAPTURE_SITES` (empty = clean)."""
    sites = COST_CAPTURE_SITES if sites is None else sites
    errors: List[str] = []
    for rel, methods in sorted(sites.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: cost-capture module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for m in methods:
            if not _calls_attribute(tree, m):
                errors.append(
                    f"{rel}: no call to profiler .{m}(...) — hot-path "
                    f"measurements would stop flowing through XLA cost "
                    f"capture")
    return errors


def check_kernel_variants(root: str = _REPO_ROOT,
                          variants: Dict[str, Tuple[Sequence[str], str]]
                          = None) -> List[str]:
    """Violations for :data:`KERNEL_VARIANTS` (empty = clean): each
    listed entry point must be defined at module level in its defining
    module AND referenced by name in its consuming module."""
    variants = KERNEL_VARIANTS if variants is None else variants
    errors: List[str] = []
    for rel, (names, consumer_rel) in sorted(variants.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: kernel-variant module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        defined = {n.name for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        consumer_path = os.path.join(root, consumer_rel)
        if os.path.exists(consumer_path):
            with open(consumer_path) as f:
                ctree = ast.parse(f.read(), filename=consumer_rel)
            referenced = {n.id for n in ast.walk(ctree)
                          if isinstance(n, ast.Name)}
        else:
            errors.append(f"{consumer_rel}: kernel-variant consumer "
                          f"missing")
            ctree, referenced = None, set()
        for name in names:
            if name not in defined:
                errors.append(f"{rel}: kernel variant {name!r} not "
                              f"defined at module level")
            elif ctree is not None and name not in referenced:
                errors.append(
                    f"{consumer_rel}: kernel variant {name!r} is "
                    f"defined but never referenced — the grid-order "
                    f"routing would silently drop it")
    return errors


def _fault_point_sites(tree: ast.Module) -> set:
    """Literal site names passed to ``fault_point(...)`` calls (plain
    name or attribute spelling)."""
    sites = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None)
        if name == "fault_point" and isinstance(node.args[0],
                                                ast.Constant):
            sites.add(node.args[0].value)
    return sites


def check_fault_sites(root: str = _REPO_ROOT,
                      sites: Dict[str, Sequence[str]] = None,
                      hot_paths: Dict[str, Sequence[str]] = None
                      ) -> List[str]:
    """Violations for :data:`FAULT_SITES` (empty = clean): every listed
    module carries every listed ``fault_point("<site>")`` call, and
    every HOT_PATHS module is covered by at least one site — a new hot
    path cannot ship uninjectable."""
    sites = FAULT_SITES if sites is None else sites
    hot_paths = HOT_PATHS if hot_paths is None else hot_paths
    errors: List[str] = []
    for rel in sorted(hot_paths):
        if rel not in sites:
            errors.append(
                f"{rel}: hot-path module has no FAULT_SITES entry — "
                f"every hot path must register a fault-injection site")
    for rel, names in sorted(sites.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: fault-site module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        found = _fault_point_sites(tree)
        for site in names:
            if site not in found:
                errors.append(
                    f"{rel}: no fault_point({site!r}) call — the hot "
                    f"path would ship uninjectable (see "
                    f"raft_tpu/resilience/faults.py)")
    return errors


def check_fault_registry(root: str = _REPO_ROOT) -> List[str]:
    """Bidirectional agreement between the sites armed in source and
    ``faults.KNOWN_SITES`` (shared derivation with graftlint's
    registry pass): an armed-but-unregistered site would never get
    matrix coverage; a registered-but-never-armed site is a dead
    registry entry."""
    derived = (_DERIVED if os.path.abspath(root) == _REPO_ROOT
               else load_analysis().registry.derive_registries(root))
    known = derived.known_sites
    if known is None:
        return ["raft_tpu/resilience/faults.py: KNOWN_SITES dict "
                "literal not found — the fault-site registry is gone"]
    errors: List[str] = []
    used = set()
    for rel, sites in sorted(derived.fault_sites.items()):
        for s in sites:
            used.add(s)
            if s not in known:
                errors.append(
                    f"{rel}: fault_point({s!r}) is armed but not "
                    f"registered in faults.KNOWN_SITES — the "
                    f"injection matrix would never test it")
    for s in sorted(set(known) - used):
        errors.append(
            f"raft_tpu/resilience/faults.py: KNOWN_SITES[{s!r}] is "
            f"never armed by any fault_point — dead registry entry")
    return errors


def _known_event_kinds(root: str) -> Optional[set]:
    """The KNOWN_EVENT_KINDS tuple literal parsed out of flight.py (the
    same static-scan pattern as the other gates — no raft_tpu import).
    None when the module/assignment is missing (reported separately)."""
    path = os.path.join(root, _FLIGHT_MODULE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        tree = ast.parse(f.read(), filename=_FLIGHT_MODULE)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            targets = [node.target.id]
        if "KNOWN_EVENT_KINDS" in targets and node.value is not None:
            try:
                val = ast.literal_eval(node.value)
            except ValueError:
                return None
            return {str(v) for v in val}
    return None


def _referenced_names(tree: ast.Module) -> set:
    """Every plain name and attribute name referenced in the module —
    covers calls, decorators (@instrument(...)), and from-imports."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif isinstance(node, ast.ImportFrom):
            names.update(a.name for a in node.names)
    return names


def check_event_sites(root: str = _REPO_ROOT,
                      sites: Dict[str, Sequence[str]] = None,
                      emitters: Dict[str, str] = None,
                      hot_paths: Dict[str, Sequence[str]] = None,
                      fault_sites: Dict[str, Sequence[str]] = None
                      ) -> List[str]:
    """Violations for :data:`EVENT_SITES` (empty = clean): every module
    in HOT_PATHS and every FAULT_SITES module must have an EVENT_SITES
    entry; each listed emitter must be referenced in the module and
    must map (via :data:`EMITTER_KINDS`) to a kind present in
    ``flight.KNOWN_EVENT_KINDS`` — a hot path that emits no timeline
    events cannot be reconstructed from a post-mortem dump."""
    sites = EVENT_SITES if sites is None else sites
    emitters = EMITTER_KINDS if emitters is None else emitters
    hot_paths = HOT_PATHS if hot_paths is None else hot_paths
    fault_sites = FAULT_SITES if fault_sites is None else fault_sites
    errors: List[str] = []
    kinds = _known_event_kinds(root)
    if kinds is None:
        errors.append(f"{_FLIGHT_MODULE}: KNOWN_EVENT_KINDS tuple not "
                      f"found — the flight-recorder vocabulary is gone")
        kinds = set()
    for emitter, kind in sorted(emitters.items()):
        if kinds and kind not in kinds:
            errors.append(
                f"EMITTER_KINDS[{emitter!r}] = {kind!r} is not a "
                f"flight.KNOWN_EVENT_KINDS kind — the gate table and "
                f"the event vocabulary have diverged")
    for rel in sorted(set(hot_paths) | set(fault_sites)):
        if rel not in sites:
            errors.append(
                f"{rel}: hot-path/fault-site module has no EVENT_SITES "
                f"entry — it would be invisible in the flight timeline")
    for rel, names in sorted(sites.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: event-site module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        referenced = _referenced_names(tree)
        for name in names:
            if name not in emitters:
                errors.append(
                    f"{rel}: EVENT_SITES emitter {name!r} is not in "
                    f"EMITTER_KINDS — unknown timeline emitter")
            if name not in referenced:
                errors.append(
                    f"{rel}: no reference to timeline emitter "
                    f"{name!r} — the module would stop emitting "
                    f"flight-recorder events")
    return errors


def check_sharded_merge(root: str = _REPO_ROOT,
                        sites: Dict[str, Sequence[str]] = None,
                        counted: Sequence[str] = None) -> List[str]:
    """Violations for :data:`SHARDED_MERGE_SITES` +
    :data:`COUNTED_COLLECTIVES` (empty = clean)."""
    sites = SHARDED_MERGE_SITES if sites is None else sites
    counted = COUNTED_COLLECTIVES if counted is None else counted
    errors: List[str] = []
    for rel, methods in sorted(sites.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: sharded-merge module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        for m in methods:
            if not _calls_attribute(tree, m):
                errors.append(
                    f"{rel}: no call to comms .{m}(...) — the sharded "
                    f"merge rounds would stop flowing through the "
                    f"collective counters")
    comms_rel = "raft_tpu/comms/comms.py"
    comms_path = os.path.join(root, comms_rel)
    if not os.path.exists(comms_path):
        errors.append(f"{comms_rel}: comms module missing")
        return errors
    with open(comms_path) as f:
        ctree = ast.parse(f.read(), filename=comms_rel)
    counted_labels = {
        node.args[0].value for node in ast.walk(ctree)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        and node.func.id == "_count" and node.args
        and isinstance(node.args[0], ast.Constant)}
    for label in counted:
        if label not in counted_labels:
            errors.append(
                f"{comms_rel}: collective {label!r} is not reported "
                f"through _count(...) — its calls/bytes would be "
                f"invisible to the metrics exporters")
    return errors


def check_quality_sites(root: str = _REPO_ROOT,
                        sites: Dict[str, Sequence[str]] = None
                        ) -> List[str]:
    """Violations for :data:`QUALITY_SITES` (empty = clean): every
    certificate/fixup/rescore module must reference its quality
    recorders — the static guarantee that fixup-rate evidence keeps
    flowing into the ``quality`` artifact blocks ``bench_report
    --check`` gates."""
    sites = QUALITY_SITES if sites is None else sites
    errors: List[str] = []
    for rel, names in sorted(sites.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: quality-site module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        referenced = _referenced_names(tree)
        for name in names:
            if name not in referenced:
                errors.append(
                    f"{rel}: no reference to quality recorder "
                    f"{name!r} — certificate/fixup telemetry would "
                    f"silently stop flowing (observability/quality.py)")
    return errors


_SERVING_DIR = "raft_tpu/serving"


def check_serving_coverage(root: str = _REPO_ROOT,
                           sites: Dict[str, Sequence[str]] = None
                           ) -> List[str]:
    """EVERY module under raft_tpu/serving/ (package __init__ excluded)
    must have an EVENT_SITES entry — a serving module invisible in the
    flight timeline cannot be reconstructed from a steady-state trace,
    and the ISSUE-7 gates promise full serving coverage. Structural,
    so a NEW serving module cannot ship unobserved by forgetting the
    table."""
    sites = EVENT_SITES if sites is None else sites
    errors: List[str] = []
    serving_dir = os.path.join(root, _SERVING_DIR)
    if not os.path.isdir(serving_dir):
        return [f"{_SERVING_DIR}/: serving package missing"]
    for name in sorted(os.listdir(serving_dir)):
        if not name.endswith(".py") or name == "__init__.py":
            continue
        rel = f"{_SERVING_DIR}/{name}"
        if rel not in sites:
            errors.append(
                f"{rel}: serving module has no EVENT_SITES entry — "
                f"every raft_tpu/serving/ module must emit timeline "
                f"events")
    return errors


def check(root: str = _REPO_ROOT,
          hot_paths: Dict[str, Sequence[str]] = None) -> List[str]:
    """Returns a list of violation messages (empty = clean)."""
    hot_paths = HOT_PATHS if hot_paths is None else hot_paths
    errors: List[str] = []
    for rel, funcs in sorted(hot_paths.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: hot-path module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        if not _imports_instrument(tree):
            errors.append(
                f"{rel}: does not import instrument from "
                f"raft_tpu.observability")
        found = {}
        for node in tree.body:  # top-level defs only
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found[node.name] = node
        for fn in funcs:
            node = found.get(fn)
            if node is None:
                errors.append(f"{rel}: expected hot-path function "
                              f"{fn!r} not found at module level")
            elif not any(_decorator_is_instrument(d)
                         for d in node.decorator_list):
                errors.append(f"{rel}: {fn}() is not decorated with "
                              f"@instrument")
    if hot_paths is HOT_PATHS:
        # the default invocation also gates the cost-capture sites, the
        # kernel-variant presence/consumption assertions, and the
        # sharded-merge collective counting; callers probing a custom
        # hot_paths table (tests) opt out
        errors.extend(check_cost_capture(root))
        errors.extend(check_kernel_variants(root))
        errors.extend(check_sharded_merge(root))
        errors.extend(check_fault_sites(root))
        errors.extend(check_fault_registry(root))
        errors.extend(check_event_sites(root))
        errors.extend(check_serving_coverage(root))
        errors.extend(check_quality_sites(root))
    return errors


def main(argv: Sequence[str] = ()) -> int:
    errors = check()
    for e in errors:
        print(f"check_instrumented: {e}", file=sys.stderr)
    if not errors:
        print(f"check_instrumented: OK — "
              f"{sum(len(v) for v in HOT_PATHS.values())} functions in "
              f"{len(HOT_PATHS)} modules instrumented; "
              f"{sum(len(v) for v in COST_CAPTURE_SITES.values())} "
              f"cost-capture sites verified; "
              f"{sum(len(v[0]) for v in KERNEL_VARIANTS.values())} "
              f"kernel variants present + consumed; "
              f"{sum(len(v) for v in SHARDED_MERGE_SITES.values())} "
              f"sharded-merge sites + "
              f"{len(COUNTED_COLLECTIVES)} counted collectives; "
              f"{sum(len(v) for v in FAULT_SITES.values())} fault-"
              f"injection sites in {len(FAULT_SITES)} modules; "
              f"{len(EVENT_SITES)} timeline-event-emitting modules; "
              f"{len(QUALITY_SITES)} quality-telemetry modules")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
