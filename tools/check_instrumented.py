#!/usr/bin/env python
"""Static check: every hot-path primitive carries @instrument.

Pure-AST, no TPU (and no raft_tpu import) needed, so it runs anywhere —
it is wired into the tier-1 suite via tests/test_observability.py. The
check asserts, per module in :data:`HOT_PATHS`:

1. the module imports ``instrument`` from ``raft_tpu.observability``, and
2. each listed function is decorated with it (bare ``@instrument`` or
   ``@instrument(...)``, plain name or attribute spelling).

Extend HOT_PATHS when a new primitive ships — forgetting to is exactly
the regression this check exists to catch: a hot path that silently
ships unobserved.

Usage: ``python tools/check_instrumented.py`` (exit 0 = clean).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Sequence

# module (repo-relative) → functions that must be instrumented
HOT_PATHS: Dict[str, Sequence[str]] = {
    "raft_tpu/matrix/select_k.py": ("select_k",),
    "raft_tpu/matrix/select_k_chunked.py": ("select_k_chunked",),
    "raft_tpu/matrix/select_k_slotted.py": ("select_k_slotted",),
    "raft_tpu/distance/pairwise.py": ("pairwise_distance",),
    "raft_tpu/distance/fused_l2nn.py": (
        "fused_l2_nn_argmin", "knn", "knn_sharded"),
    "raft_tpu/distance/knn_fused.py": ("knn_fused",),
    "raft_tpu/sparse/tiled.py": ("tile_csr", "tile_csr_pairs"),
    "raft_tpu/sparse/sharded.py": ("spmv_sharded", "spmm_sharded"),
    "raft_tpu/solver/linear_assignment.py": ("solve_lap",),
}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _decorator_is_instrument(dec: ast.expr) -> bool:
    """True for @instrument, @instrument(...), @observability.instrument,
    and @raft_tpu.observability.instrument(...)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Attribute):
        return dec.attr == "instrument"
    return isinstance(dec, ast.Name) and dec.id == "instrument"


def _imports_instrument(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if (node.module or "").startswith("raft_tpu.observability"):
                if any(a.name == "instrument" for a in node.names):
                    return True
        elif isinstance(node, ast.Import):
            if any(a.name.startswith("raft_tpu.observability")
                   for a in node.names):
                return True
    return False


def check(root: str = _REPO_ROOT,
          hot_paths: Dict[str, Sequence[str]] = None) -> List[str]:
    """Returns a list of violation messages (empty = clean)."""
    hot_paths = HOT_PATHS if hot_paths is None else hot_paths
    errors: List[str] = []
    for rel, funcs in sorted(hot_paths.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: hot-path module missing")
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        if not _imports_instrument(tree):
            errors.append(
                f"{rel}: does not import instrument from "
                f"raft_tpu.observability")
        found = {}
        for node in tree.body:  # top-level defs only
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found[node.name] = node
        for fn in funcs:
            node = found.get(fn)
            if node is None:
                errors.append(f"{rel}: expected hot-path function "
                              f"{fn!r} not found at module level")
            elif not any(_decorator_is_instrument(d)
                         for d in node.decorator_list):
                errors.append(f"{rel}: {fn}() is not decorated with "
                              f"@instrument")
    return errors


def main(argv: Sequence[str] = ()) -> int:
    errors = check()
    for e in errors:
        print(f"check_instrumented: {e}", file=sys.stderr)
    if not errors:
        print(f"check_instrumented: OK — "
              f"{sum(len(v) for v in HOT_PATHS.values())} functions in "
              f"{len(HOT_PATHS)} modules instrumented")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
