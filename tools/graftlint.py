#!/usr/bin/env python
"""graftlint — whole-program AST lint gate for the raft_tpu tree.

Front door for :mod:`raft_tpu.analysis` (module loader + call graph +
pass registry). Three flagship passes: ``trace-purity`` (host-sync /
retrace hazards reachable from jit/shard_map/pallas_call/_aot_call
entry points), ``lock-discipline`` (lock-order inversions, blocking
calls under a held lock, unlocked cross-thread module state) and
``registry`` (fault sites / event kinds / hot paths / env knobs
derived from source and diffed against every declared registry).

Findings are gated against the baseline-suppression file
(``tools/graftlint_baseline.json``): every suppression carries a
mandatory reason string. Exit 0 = no unsuppressed error findings.

Usage::

    python tools/graftlint.py                  # lint, human output
    python tools/graftlint.py --json           # + write LINT_REPORT.json
    python tools/graftlint.py --passes registry
    python tools/graftlint.py --suggest-baseline  # suppression stubs

The analysis package is loaded standalone (no ``raft_tpu`` /jax
import — pure stdlib AST), so the gate runs anywhere the source
tree exists; it is wired into tier-1 via tests/test_analysis.py and
into ``bench_report --check`` via the ``[lint]`` gate over
``LINT_REPORT.json``.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
from typing import Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT_NAME = "LINT_REPORT.json"
REPORT_SCHEMA = 1


def load_analysis(root: str = _REPO_ROOT):
    """Import ``raft_tpu/analysis`` as the standalone package
    ``raft_tpu_analysis`` — same files, but without executing
    ``raft_tpu/__init__.py`` (which imports jax). Tools stay runnable
    on a bare checkout; tests import ``raft_tpu.analysis`` normally
    and the two resolve to identical sources."""
    name = "raft_tpu_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(root, "raft_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _git_commit(root: str) -> str:
    try:
        r = subprocess.run(["git", "-C", root, "rev-parse", "--short",
                            "HEAD"], capture_output=True, text=True,
                           timeout=10)
        if r.returncode == 0:
            return r.stdout.strip()
    except Exception:
        pass
    return "unknown"


def run_lint(root: str = _REPO_ROOT,
             passes: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None):
    """→ (report dict, unsuppressed-error findings, baseline).
    The report is exactly what ``--json`` writes."""
    analysis = load_analysis(root)
    baseline_path = baseline_path or os.path.join(
        root, "tools", "graftlint_baseline.json")
    baseline = analysis.Baseline.load(baseline_path)
    by_pass = analysis.run_passes(root, names=passes)

    all_findings = [f for fs in by_pass.values() for f in fs]
    unsuppressed, suppressed, stale = baseline.apply(all_findings)
    errors = [f for f in unsuppressed if f.severity == "error"]
    warnings = [f for f in unsuppressed if f.severity != "error"]

    sup_fps = {f.fingerprint for f in suppressed}
    pass_blocks = {}
    for name, fs in sorted(by_pass.items()):
        un = [f for f in fs if f.fingerprint not in sup_fps]
        rules = {}
        for f in un:
            rules[f.rule] = rules.get(f.rule, 0) + 1
        pass_blocks[name] = {
            "findings": len(fs),
            "suppressed": len(fs) - len(un),
            "unsuppressed": len(un),
            "unsuppressed_errors": sum(1 for f in un
                                       if f.severity == "error"),
            "rules": dict(sorted(rules.items())),
        }
    report = {
        "schema": REPORT_SCHEMA,
        "tool": "graftlint",
        "commit": _git_commit(root),
        "ok": not errors,
        "passes": pass_blocks,
        "total_findings": len(all_findings),
        "suppressed": len(suppressed),
        "unsuppressed_errors": len(errors),
        "unsuppressed_warnings": len(warnings),
        "stale_baseline_entries": stale,
        "baseline_entries": len(baseline.entries),
        "findings": [
            {"pass": f.pass_name, "rule": f.rule, "file": f.rel,
             "line": f.line, "severity": f.severity,
             "message": f.message, "fingerprint": f.fingerprint}
            for f in sorted(unsuppressed,
                            key=lambda f: (f.pass_name, f.rel, f.line,
                                           f.rule, f.where))],
    }
    return report, errors, warnings, stale, baseline


def main(argv: Sequence[str] = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        prog="graftlint")
    p.add_argument("--root", default=_REPO_ROOT,
                   help="repo root to lint (default: this checkout)")
    p.add_argument("--passes", default=None,
                   help="comma-separated pass subset (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline-suppression file (default: "
                        "tools/graftlint_baseline.json)")
    p.add_argument("--json", nargs="?", const="", default=None,
                   metavar="PATH",
                   help=f"write the machine report (default path: "
                        f"<root>/{REPORT_NAME})")
    p.add_argument("--list-passes", action="store_true",
                   help="print the registered pass names and exit")
    p.add_argument("--suggest-baseline", action="store_true",
                   help="print suppression stubs for every "
                        "unsuppressed finding (fill in the reasons!)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="summary line only")
    args = p.parse_args(argv)

    analysis = load_analysis(args.root)
    if args.list_passes:
        for name in analysis.all_passes():
            print(name)
        return 0

    passes = (args.passes.split(",") if args.passes else None)
    report, errors, warnings, stale, _baseline = run_lint(
        args.root, passes=passes, baseline_path=args.baseline)

    if args.suggest_baseline:
        stubs = [{"fingerprint": f["fingerprint"],
                  "reason": "<why this is acceptable>"}
                 for f in report["findings"]]
        print(json.dumps({"schema": 1, "suppressions": stubs},
                         indent=1))
        return 0

    if not args.quiet:
        for f in report["findings"]:
            sev = "" if f["severity"] == "error" else " [warning]"
            print(f"graftlint: {f['file']}:{f['line']}: "
                  f"{f['rule']}{sev}: {f['message']}",
                  file=sys.stderr)
        for fp in stale:
            print(f"graftlint: stale baseline entry (no matching "
                  f"finding — clean it up): {fp}", file=sys.stderr)
    counts = ", ".join(
        f"{name}: {blk['unsuppressed']} unsuppressed"
        f" ({blk['suppressed']} baselined)"
        for name, blk in report["passes"].items())
    verdict = "OK" if report["ok"] else "FAIL"
    print(f"graftlint: {verdict} — {counts}; "
          f"{report['unsuppressed_errors']} gating errors, "
          f"{report['unsuppressed_warnings']} warnings, "
          f"{len(stale)} stale baseline entries")

    if args.json is not None:
        path = args.json or os.path.join(args.root, REPORT_NAME)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"graftlint: wrote {path}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
