#!/usr/bin/env python
"""statusz — one-call operator health snapshot of a raft_tpu process.

Renders, from the live in-process observability surfaces, the page an
operator reads FIRST when paged:

- **quality**: certificate fixup rates per site (twin-pool failures /
  checks), q8 rescore-pool widths, IVF certificate-rerun counts, and
  the online shadow recall gauge + breach count — the result-quality
  plane (``raft_tpu.observability.quality``);
- **latency**: p50/p99 of every ``*_seconds`` histogram in the
  registry (bucket-interpolated) — serving request latency included;
- **degradations**: the resilience ladder's step count — a nonzero
  value means some hot path is running below its configured rung;
- **forensics**: flight-ring drop count (truncated evidence must be
  visible before anyone trusts a dump), blackbox write stats, watchdog
  tick/stall counts, and the prior run's crash verdict when the engine
  booted over an unclean blackbox;
- **flight tail**: the newest flight-recorder events, time-ordered —
  the last thing that happened before you looked;
- the full registry summary table for everything else.

Import :func:`render_statusz` inside a serving process (tests and
``benchmarks/bench_serving.py`` do), or run ``python tools/statusz.py
--demo`` for a self-contained deterministic serving round followed by
its own snapshot — the zero-to-evidence smoke an operator can run on
any checkout without a TPU.
"""

from __future__ import annotations

import argparse
import io
import os
import sys
from typing import Optional, Sequence

# runnable as a script from anywhere: the repo root precedes any
# installed raft_tpu (same convention as benchmarks/_common.py)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

_TAIL_DEFAULT = 16


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.3f} ms" if v < 10 else f"{v:.3f} s"


def render_statusz(registry=None, recorder=None, engine=None,
                   tail: int = _TAIL_DEFAULT) -> str:
    """The health snapshot as one printable string. Never raises — a
    broken subsystem renders as a note, not a crash (this page is what
    you read WHILE things are broken)."""
    from raft_tpu.observability import quality as q
    from raft_tpu.observability.exporters import summary_table
    from raft_tpu.observability.flight import get_flight_recorder
    from raft_tpu.observability.metrics import Histogram, get_registry

    reg = registry if registry is not None else get_registry()
    rec = recorder if recorder is not None else get_flight_recorder()
    out = io.StringIO()
    out.write("raft_tpu statusz\n================\n\n")

    # ---- quality plane ------------------------------------------------
    out.write("quality (certificate / fixup / shadow recall)\n")
    out.write("---------------------------------------------\n")
    try:
        block = q.quality_block(registry=reg)
        if block is None:
            out.write("(no quality telemetry recorded yet)\n")
        else:
            out.write(f"fixup_rate      {block['fixup_rate']:.6f}  "
                      f"({block['certificate_fixups']} fixups / "
                      f"{block['certificate_checks']} checks)\n")
            for site, s in sorted(block.get("sites", {}).items()):
                extra = (f" reruns={s['cert_reruns']}"
                         if "cert_reruns" in s else "")
                out.write(f"  {site:<32} rate={s['fixup_rate']:.6f} "
                          f"fixups={s.get('fixups', 0)} "
                          f"checks={s.get('checks', 0)}{extra}\n")
            for site, p in sorted(
                    block.get("rescore_pool_widths", {}).items()):
                out.write(f"  {site:<32} rescore pool mean width "
                          f"{p['mean']:g} over {p['count']} batch(es)\n")
            if "shadow_recall" in block:
                out.write(f"shadow recall   {block['shadow_recall']:.4f}"
                          f" over {block.get('shadow_samples', 0)} "
                          f"sample(s), "
                          f"{block.get('shadow_breaches', 0)} "
                          f"breach(es)\n")
            else:
                out.write("shadow recall   (sampler off — set "
                          "RAFT_TPU_SERVING_SHADOW_FRAC)\n")
    except Exception as e:
        out.write(f"(quality section unavailable: {e})\n")

    # ---- latency percentiles ------------------------------------------
    out.write("\nlatency percentiles (registry histograms)\n")
    out.write("-----------------------------------------\n")
    try:
        any_h = False
        for metric in reg.collect():
            if not isinstance(metric, Histogram) or not metric.count:
                continue
            if not metric.name.endswith("_seconds"):
                continue
            any_h = True
            label_s = ",".join(f"{k}={v}" for k, v in
                               sorted(metric.labels.items()))
            name = metric.name + (f"{{{label_s}}}" if label_s else "")
            out.write(f"  {name:<48} p50={_fmt_s(metric.percentile(50))}"
                      f"  p99={_fmt_s(metric.percentile(99))}"
                      f"  n={metric.count}\n")
        if not any_h:
            out.write("(no time histograms recorded yet)\n")
    except Exception as e:
        out.write(f"(latency section unavailable: {e})\n")

    # ---- engine + degradations ----------------------------------------
    if engine is not None:
        out.write("\nserving engine\n--------------\n")
        try:
            st = engine.snapshot_stats()
            for key in ("queue_rows", "batches", "shed",
                        "expired_in_queue", "requeued", "p50_ms",
                        "p99_ms", "shadow_recall", "shadow_samples",
                        "generation", "compile_misses"):
                if key in st and st[key] is not None:
                    v = st[key]
                    out.write(f"  {key:<18} "
                              f"{v:.4f}\n" if isinstance(v, float)
                              else f"  {key:<18} {v}\n")
        except Exception as e:
            out.write(f"(engine stats unavailable: {e})\n")
    # ---- durability & recovery ----------------------------------------
    out.write("\ndurability (WAL / checkpoints / recovery)\n")
    out.write("-----------------------------------------\n")
    try:
        plane = None
        if engine is not None:
            mut = getattr(engine, "mutable", None)
            plane = getattr(mut, "durability", None) if mut else None
        if plane is not None:
            st = plane.stats()
            out.write(f"wal sync={st.get('sync')}  "
                      f"last_lsn={st.get('last_lsn')}  "
                      f"durable_lsn={st.get('durable_lsn')}  "
                      f"segments={st.get('segments')}\n")
            out.write(f"checkpoints {st.get('checkpoints', 0)} "
                      f"(newest lsn={st.get('checkpoint_lsn', '-')}, "
                      f"gen={st.get('checkpoint_generation', '-')})\n")
        else:
            out.write("(no durability plane attached — "
                      "durable=False)\n")
        from raft_tpu.mutable.checkpoint import last_recovery

        rec_info = last_recovery()
        if rec_info is not None:
            out.write(f"last recovery   {rec_info['seconds'] * 1e3:.1f}"
                      f" ms: {rec_info['replayed_records']} record(s) "
                      f"replayed over checkpoint "
                      f"lsn={rec_info['checkpoint_lsn']}, "
                      f"{rec_info['truncated_bytes']} torn byte(s) "
                      f"truncated\n")
        else:
            out.write("last recovery   (none this process)\n")
    except Exception as e:
        out.write(f"(durability section unavailable: {e})\n")

    # ---- SLO burn state ------------------------------------------------
    out.write("\nSLO burn state\n--------------\n")
    try:
        slo = getattr(engine, "slo", None) if engine is not None \
            else None
        if slo is None:
            out.write("(no SLO engine attached)\n")
        else:
            st = slo.status()
            out.write(f"healthy={st['healthy']}  "
                      f"covered={st['covered_s']:.1f}s\n")
            for obj in st.get("objectives", []):
                out.write(f"  {obj['slo']:<18} objective="
                          f"{obj['objective']:.4f}\n")
                for rung in obj.get("windows", []):
                    fast = rung.get("burn_fast")
                    slow = rung.get("burn_slow")
                    out.write(
                        f"    {rung['severity']:<8} "
                        f"fast={'-' if fast is None else f'{fast:.2f}'}"
                        f"  slow="
                        f"{'-' if slow is None else f'{slow:.2f}'}"
                        f"  x{rung['factor']:g}"
                        + ("  FIRING" if rung.get("firing") else "")
                        + "\n")
            for alert in st.get("active_alerts", []):
                out.write(f"  ALERT {alert['slo']}/{alert['severity']}"
                          f" burn={alert.get('burn_fast', 0):.2f}\n")
    except Exception as e:
        out.write(f"(SLO section unavailable: {e})\n")

    # ---- explain ring --------------------------------------------------
    out.write("\nexplain ring (newest records)\n")
    out.write("-----------------------------\n")
    try:
        from raft_tpu.observability.explain import explain_records

        records = explain_records(limit=4)
        if not records:
            out.write("(no explain records — set RAFT_TPU_EXPLAIN_FRAC"
                      " or submit(explain=True))\n")
        for r in records:
            margins = r.get("margins", {})
            m_min = min((m["min"] for m in margins.values()),
                        default=None)
            out.write(f"  rid={r.get('rids', ['-'])[0]:<8} "
                      f"plane={r.get('plane', '?'):<9} "
                      f"outcome={r.get('outcome', '?'):<8} "
                      f"margin_min="
                      f"{'-' if m_min is None else f'{m_min:.4g}'}"
                      f"  wall={r.get('wall_s', 0) * 1e3:.1f}ms\n")
    except Exception as e:
        out.write(f"(explain section unavailable: {e})\n")

    # ---- forensics (blackbox / watchdog) -------------------------------
    out.write("\nforensics (blackbox / watchdog)\n")
    out.write("-------------------------------\n")
    try:
        from raft_tpu.observability import blackbox as bb_mod
        from raft_tpu.observability.flight import (FLIGHT_DROPPED,
                                                   sync_dropped_metric)

        dropped = sync_dropped_metric(rec)
        out.write(f"flight ring     seq={rec.seq} dropped={dropped} "
                  f"({FLIGHT_DROPPED})\n")
        bb = bb_mod.active()
        if bb is not None:
            st = bb.stats()
            out.write(f"blackbox        {st['path']}: "
                      f"{st['records']} record(s), "
                      f"{st['bytes_written']} bytes into "
                      f"{st['ring_bytes']}-byte ring, "
                      f"{st['append_seconds'] * 1e3:.2f} ms append "
                      f"time\n")
        else:
            out.write("blackbox        (off — set "
                      "RAFT_TPU_BLACKBOX_PATH)\n")
        wd = getattr(engine, "_watchdog", None) if engine is not None \
            else None
        if wd is not None:
            st = wd.stats()
            out.write(f"watchdog        interval={st['interval_s']:g}s "
                      f"ticks={st['ticks']} stalls={st['stalls']}"
                      + ("  STALL ACTIVE" if st["stall_active"]
                         else "") + "\n")
        else:
            out.write("watchdog        (off — set "
                      "RAFT_TPU_WATCHDOG_S)\n")
        report = getattr(engine, "crash_report", None) \
            if engine is not None else None
        if report is not None:
            out.write(f"prior run       verdict={report.get('verdict')}"
                      f" ({report.get('records')} record(s) recovered"
                      f" — see /crashz)\n")
    except Exception as e:
        out.write(f"(forensics section unavailable: {e})\n")

    out.write("\ndegradations\n------------\n")
    try:
        from raft_tpu.resilience import degradation_count

        out.write(f"resilience ladder steps this process: "
                  f"{degradation_count()}\n")
    except Exception as e:
        out.write(f"(degradation count unavailable: {e})\n")

    # ---- registry summary ---------------------------------------------
    out.write("\nmetrics registry\n----------------\n")
    try:
        out.write(summary_table(reg))
    except Exception as e:
        out.write(f"(registry summary unavailable: {e})\n")

    # ---- flight tail ---------------------------------------------------
    out.write(f"\nflight tail (newest {tail} events)\n")
    out.write("----------------------------------\n")
    try:
        events = rec.tail(tail)
        if not events:
            out.write("(flight recorder empty)\n")
        for ev in events:
            extra = ev.get("step") or ev.get("action") or \
                ev.get("event") or ""
            out.write(f"  {ev.get('ts', 0.0):>12.6f}  "
                      f"{ev.get('kind', '?'):<11} "
                      f"{str(ev.get('name', '?')):<28} "
                      f"lane={ev.get('lane', '-')}"
                      + (f" [{extra}]" if extra else "") + "\n")
    except Exception as e:
        out.write(f"(flight tail unavailable: {e})\n")
    return out.getvalue()


def _demo_round() -> "object":
    """A tiny deterministic serving round (CPU-sized) so a bare
    checkout produces a populated statusz page: brute engine, shadow
    sampling at 100%, a handful of ragged requests."""
    import numpy as np

    from raft_tpu.distance.knn_fused import prepare_knn_index
    from raft_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    y = rng.normal(size=(2048, 32)).astype(np.float32)
    idx = prepare_knn_index(y, passes=3, T=256, Qb=32, g=2)
    eng = ServingEngine(idx, k=8, buckets=(8, 16),
                        flush_interval_s=0.002, shadow_frac=1.0)
    eng.start()
    futs = [eng.submit(rng.normal(size=(n, 32)).astype(np.float32))
            for n in (1, 4, 8, 3, 6)]
    eng.flush()
    for f in futs:
        f.result(timeout=60)
    if eng.shadow is not None:
        eng.shadow.flush()
    return eng


def main(argv: Sequence[str] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--demo", action="store_true",
                   help="run a deterministic CPU serving round first, "
                        "then render its snapshot")
    p.add_argument("--tail", type=int, default=_TAIL_DEFAULT,
                   help="flight-tail length")
    args = p.parse_args(argv)

    engine = None
    if args.demo:
        engine = _demo_round()
    sys.stdout.write(render_statusz(engine=engine, tail=args.tail))
    if engine is not None:
        engine.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
