#!/usr/bin/env python
"""Perf-evidence pipeline: BENCH_*.json → one trajectory + CI gate.

The repo accumulates one ``BENCH_r<NN>.json`` per measurement round (the
driver wraps ``bench.py``'s one-line JSON in ``{"n", "cmd", "rc",
"tail", "parsed"}``) plus ``BENCH_LAST_GOOD.json`` — the last known-good
flat record. This tool turns that pile of disconnected artifacts into:

1. a **trajectory report** (default): per-round series of the headline
   metric and its sub-metrics (p1/p3 GB/s), commit labels, degraded
   flags, and — for artifacts produced after the cost-model PR — the
   static FLOPs/bytes and %-of-roofline columns ``benchmark.Fixture.run``
   now emits;
2. a **regression gate** (``--check``): the newest round is compared
   against BENCH_LAST_GOOD with a configurable threshold (a degraded
   newest round is a no-op — outage artifacts are history, not gates).
   Exit 0 = pass or nothing to gate (no new comparable artifact — the
   tier-1 no-op), exit 1 = regression, exit 2 = a gateable artifact
   exists but the baseline is missing;
3. a **drift gate** (part of ``--check``): DRIFT_LEDGER.json — the
   model-vs-measured ledger ``benchmark.Fixture.run`` records — is
   scanned per site; a site whose MEASURED entry has the cost model's
   predicted seconds off by more than ``--drift-band`` (default 3x
   either way) fails the gate. Modeled-only entries (``measured:
   false`` — the CPU suite) are never drift-gated, and artifacts carry
   ``drift_checked`` so calibrated rounds are tellable from modeled
   ones.

Degraded rounds (tunnel down, CPU fallback, cached re-emission) are
shown in the trajectory but never gated — gating an outage artifact
against a TPU baseline would fail every PR the tunnel is down for.

Usage::

    python tools/bench_report.py                  # trajectory report
    python tools/bench_report.py --check          # CI gate (tier-1)
    python tools/bench_report.py --check --threshold 0.10
    python tools/bench_report.py --dir /path/to/artifacts --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROUND_GLOB = "BENCH_r*.json"
MULTICHIP_GLOB = "MULTICHIP_r*.json"
SERVING_GLOB = "SERVING_r*.json"
SERVING_NAME = "BENCH_SERVING.json"
ANN_GLOB = "ANN_r*.json"
ANN_NAME = "BENCH_ANN.json"
MUTATION_GLOB = "MUTATION_r*.json"
MUTATION_NAME = "BENCH_MUTATION.json"
RECOVERY_GLOB = "RECOVERY_r*.json"
RECOVERY_NAME = "BENCH_RECOVERY.json"
# recall@k may drop at most this much ABSOLUTE between rounds (recall
# is platform-independent math, so the trend gates modeled rounds too —
# only the ms columns are speed and measured-only)
ANN_RECALL_SLACK = 0.02
#: relative slack on the ANN fine-scan overread trend: the newest
#: round's best modeled list-major overread win may not fall more than
#: this fraction below the previous comparable round's (ISSUE 14)
ANN_OVERREAD_SLACK = 0.2
BASELINE_NAME = "BENCH_LAST_GOOD.json"
DRIFT_LEDGER_NAME = "DRIFT_LEDGER.json"
DEFAULT_THRESHOLD = 0.15   # 15% relative drop (or slowdown) fails
# flag a site when the cost model's predicted seconds and the MEASURED
# seconds disagree by more than this factor either way. Mirror of
# raft_tpu.observability.timeline.DRIFT_BAND (this tool stays
# raft_tpu-import-free); tests/test_flight.py pins the two equal.
DRIFT_BAND = 3.0

# named single-shot artifacts whose numbers predate arbitrary amounts of
# later work: the report flags the ones whose last-touching commit is
# older than the last-good measurement's commit instead of silently
# presenting them as current (SELECT_K_MATRIX / PALLAS_SMOKE / TPU_FUZZ
# all predate multiple perf rounds at the time this gate shipped)
NAMED_ARTIFACTS = ("SELECT_K_MATRIX.json", "PALLAS_SMOKE.json",
                   "TPU_FUZZ.json", "BUSBW_BENCH.json",
                   "BENCH_SERVING.json", "BENCH_ANN.json",
                   "BENCH_MUTATION.json", "BENCH_RECOVERY.json",
                   "LINT_REPORT.json")

#: graftlint machine report (tools/graftlint.py --json): the [lint]
#: gate — nonzero unsuppressed error findings REGRESS the check
LINT_NAME = "LINT_REPORT.json"

# cost-model fields Fixture.run emits into BENCH artifacts (PR 2+)
COST_FIELDS = ("flops", "bytes_accessed", "arithmetic_intensity",
               "peak_hbm_bytes", "bound", "roofline_frac")

PASS, REGRESS, MISSING_BASELINE, SKIP = ("pass", "regress",
                                         "missing-baseline", "skip")

#: quantized-index-streaming gate: int8 rows must model ≤ this fraction
#: of the bf16 baseline's streamed database bytes (the point of the
#: dtype — 1/2 at passes=1 before the scale-tile overhead), and their
#: id-parity flag must hold
QUANTIZED_RATIO_CEIL = 0.55

#: PQ-tier gate (the quantized gate extended to product quantization):
#: the modeled codes-slab stream must be ≤ this fraction of the f32
#: slab stream (1/16 at 8-bit codes with pq_dim = d/4, 1/32 at 4-bit)
#: AND the id-parity-after-rescore flag must hold. Mirror of
#: benchmarks/bench_ann.PQ_RATIO_CEIL (this tool stays
#: raft_tpu-import-free); tests pin the two equal.
PQ_RATIO_CEIL = 0.10

#: PQ certificate-rerun gate (ISSUE 19): on the diffuse-Gaussian
#: (worst-case) benchmark distribution the certificate's exact-rerun
#: fraction at the recall floor must be ≤ this ceiling, and must not
#: rise more than ``PQ_RERUN_SLACK`` absolute vs the previous
#: comparable round. Mirror of benchmarks/bench_ann.PQ_RERUN_CEIL
#: (this tool stays raft_tpu-import-free); tests pin the two equal.
PQ_RERUN_CEIL = 0.10
PQ_RERUN_SLACK = 0.05

#: quality-telemetry gate: any recall a ``quality`` block carries
#: (online shadow recall, offline ANN recall) must reach this floor —
#: the same 0.95 the ANN frontier gate enforces. Mirror of
#: raft_tpu.observability.quality.DEFAULT_SHADOW_FLOOR (this tool
#: stays raft_tpu-import-free); tests pin the two equal.
QUALITY_RECALL_FLOOR = 0.95


def percentile(values, q: float) -> float:
    """Linear-interpolated percentile (numpy's default method). Mirror
    of ``raft_tpu.observability.metrics.percentile`` — this tool stays
    raft_tpu-import-free, so the implementation is duplicated and
    tests/test_quality.py pins the two equal on random data."""
    vs = sorted(float(v) for v in values)
    if not vs:
        raise ValueError("percentile: empty sample set")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile: q={q} outside [0, 100]")
    if len(vs) == 1:
        return vs[0]
    rank = (len(vs) - 1) * (q / 100.0)
    lo = int(rank)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (rank - lo)


def load_record(path: str) -> Optional[Dict]:
    """Flat benchmark record from a BENCH artifact: unwraps the driver's
    ``{"parsed": ...}`` envelope; None for unreadable/recordless files."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed", data)
    if not isinstance(rec, dict) or "metric" not in rec:
        return None
    return rec


def normalize_metric(name: str) -> str:
    """Comparison key for a metric name: the bare primitive+shape, with
    parenthesized platform notes and bracketed cache/outage annotations
    stripped — ``"fused_l2nn+select_k top-64 2048x... (tpu, ...) [CACHED
    ...]"`` and its BENCH_LAST_GOOD spelling compare equal."""
    base = re.sub(r"\s*\[[^\]]*\]", "", name)
    base = re.sub(r"\s*\([^)]*\)", "", base)
    return base.strip()


def higher_is_better(unit: str) -> bool:
    """GB/s-style rates improve upward; ms/seconds improve downward."""
    return unit.strip().lower().endswith("/s")


def collect_rounds(directory: str) -> List[Tuple[int, str, Optional[Dict]]]:
    """(round number, path, record) for every BENCH_r*.json, in round
    order; unparseable files keep their slot with record=None so the
    trajectory shows the hole instead of silently closing it."""
    out = []
    for path in glob.glob(os.path.join(directory, ROUND_GLOB)):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_record(path)))
    out.sort(key=lambda t: t[0])
    return out


def load_multichip(path: str) -> Optional[Dict]:
    """Flat multichip record: unwraps the driver's envelope like
    :func:`load_record`, but multichip rounds are NOT required to carry
    a perf metric — the early rounds are bare ``{n_devices, rc, ok}``
    dryrun verdicts and must stay visible in the trajectory."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed")
    if isinstance(rec, dict) and ("ok" in rec or "strategies" in rec):
        merged = dict(data)
        merged.update(rec)
        return merged
    if "ok" in data or "n_devices" in data or "strategies" in data:
        return data
    return None


def collect_multichip(directory: str
                      ) -> List[Tuple[int, str, Optional[Dict]]]:
    out = []
    for path in glob.glob(os.path.join(directory, MULTICHIP_GLOB)):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_multichip(path)))
    out.sort(key=lambda t: t[0])
    return out


def _best_busbw(rec: Dict) -> Optional[float]:
    strategies = rec.get("strategies")
    if not isinstance(strategies, dict):
        return None
    fracs = [s.get("busbw_frac") for s in strategies.values()
             if isinstance(s, dict)
             and isinstance(s.get("busbw_frac"), (int, float))]
    return max(fracs) if fracs else None


def check_multichip(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
                    threshold: float = DEFAULT_THRESHOLD
                    ) -> Tuple[str, str]:
    """Gate the MULTICHIP trend: the newest parseable round must be
    ``ok`` (a failed distributed dryrun/bench is a regression, not a
    footnote), and when the newest AND a previous round both carry
    MEASURED sharded-KNN throughput, the newest must hold the value
    within ``threshold`` (modeled off-TPU rounds are evidence of model
    shape, not chip speed — never gated against measured history)."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no MULTICHIP artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest MULTICHIP round skipped (no devices)"
    mrd = newest.get("resilience_degradations")
    if isinstance(mrd, (int, float)) and mrd > 0:
        return SKIP, (
            f"latest MULTICHIP round recorded {mrd:g} resilience "
            f"degradation ladder step(s) — a degraded run is history, "
            f"never gated and never baseline material")
    if not newest.get("ok", True):
        return REGRESS, ("latest MULTICHIP round failed (ok=false) — "
                         "the distributed path regressed")
    value = newest.get("value")
    if not newest.get("measured") or not isinstance(value, (int, float)):
        return PASS, ("latest MULTICHIP round ok"
                      + ("" if newest.get("measured")
                         else " (modeled — not gated on speed)"))
    prev = None
    for _, _, rec in reversed(rounds[:-1]):
        if (rec is not None and rec.get("measured")
                and isinstance(rec.get("value"), (int, float))
                and rec.get("unit", "GB/s") == newest.get("unit",
                                                          "GB/s")):
            prev = rec
            break
    if prev is None:
        return PASS, (f"multichip ok: {value:g} "
                      f"{newest.get('unit', 'GB/s')} (first measured "
                      f"round — nothing to trend against)")
    floor = prev["value"] * (1.0 - threshold)
    if value < floor:
        return REGRESS, (
            f"MULTICHIP REGRESSION: {value:g} < {floor:g} "
            f"(previous measured {prev['value']:g} − {threshold:.0%})")
    msg = (f"multichip ok: {value:g} {newest.get('unit', 'GB/s')} vs "
           f"previous {prev['value']:g}")
    bw, pbw = _best_busbw(newest), _best_busbw(prev)
    if bw is not None and pbw is not None and pbw > 0:
        if bw < pbw * (1.0 - threshold):
            return REGRESS, (
                f"MULTICHIP BUSBW REGRESSION: busbw_frac {bw:.3g} < "
                f"{pbw * (1.0 - threshold):.3g} (previous {pbw:.3g} − "
                f"{threshold:.0%}) — the merge lost ICI ground even "
                f"though the headline holds")
        msg += f"; busbw_frac {bw:.3g} vs {pbw:.3g}"
    return PASS, msg


def load_serving(path: str) -> Optional[Dict]:
    """Flat serving-SLO record (benchmarks/bench_serving.py): unwraps
    the driver's envelope like :func:`load_multichip`. A record must
    carry at least an ``ok`` verdict or a latency/throughput field to
    count."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed")
    if isinstance(rec, dict) and ("ok" in rec or "p99_ms" in rec
                                  or "throughput_qps" in rec):
        merged = dict(data)
        merged.update(rec)
        return merged
    if "ok" in data or "p99_ms" in data or "throughput_qps" in data:
        return data
    return None


def collect_serving(directory: str
                    ) -> List[Tuple[int, str, Optional[Dict]]]:
    """(round, path, record) for every SERVING_r*.json, in round order,
    plus the bare BENCH_SERVING.json (when present) as the NEWEST
    entry — the current run's artifact gates even before a driver wraps
    it into a numbered round."""
    out = []
    for path in glob.glob(os.path.join(directory, SERVING_GLOB)):
        m = re.search(r"SERVING_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_serving(path)))
    out.sort(key=lambda t: t[0])
    bare = os.path.join(directory, SERVING_NAME)
    if os.path.exists(bare):
        n = (out[-1][0] + 1) if out else 1
        out.append((n, bare, load_serving(bare)))
    return out


def check_serving(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
                  threshold: float = DEFAULT_THRESHOLD
                  ) -> Tuple[str, str]:
    """Gate the serving-SLO trend (BENCH_SERVING / SERVING_r*):

    - the newest parseable round must be ``ok`` (correctness parity +
      no compile miss after warm-up — a broken serving path is a
      regression, not a footnote);
    - degraded rounds (nonzero resilience degradations — sheds, ladder
      walks) are SKIPped: outage evidence is history, never a gate;
    - only MEASURED rounds are speed-gated: when the newest and a
      previous measured round both carry p99 latency / throughput, p99
      must not grow past ``threshold`` and throughput must not drop
      past it. Modeled (off-TPU) rounds pass on ``ok`` alone."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no serving artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest serving round skipped"
    rd = newest.get("resilience_degradations")
    if isinstance(rd, (int, float)) and rd > 0:
        return SKIP, (
            f"latest serving round recorded {rd:g} degradation "
            f"step(s) (sheds/ladder walks) — a degraded run is "
            f"history, never gated and never baseline material")
    if not newest.get("ok", True):
        return REGRESS, ("latest serving round failed (ok=false) — "
                         "the serving path regressed")
    misses = newest.get("compile_misses_after_warmup")
    if isinstance(misses, (int, float)) and misses > 0:
        return REGRESS, (
            f"latest serving round paid {misses:g} AOT compile "
            f"miss(es) AFTER warm-up — a live request traced/compiled, "
            f"the exact latency cliff the bucket ladder exists to "
            f"prevent")
    p99 = newest.get("p99_ms")
    qps = newest.get("throughput_qps")
    if not newest.get("measured"):
        return PASS, ("latest serving round ok (modeled — not gated "
                      "on speed)")
    prev = None
    for _, _, rec in reversed(rounds[:-1]):
        if (rec is not None and rec.get("measured")
                and not rec.get("skipped")
                and isinstance(rec.get("p99_ms"), (int, float))):
            prev = rec
            break
    if prev is None:
        return PASS, (f"serving ok: p99 {p99} ms, {qps} req/s (first "
                      f"measured round — nothing to trend against)")
    msgs = []
    if isinstance(p99, (int, float)) and \
            isinstance(prev.get("p99_ms"), (int, float)):
        ceil = prev["p99_ms"] * (1.0 + threshold)
        if p99 > ceil:
            return REGRESS, (
                f"SERVING P99 REGRESSION: {p99:g} ms > {ceil:g} "
                f"(previous measured {prev['p99_ms']:g} + "
                f"{threshold:.0%})")
        msgs.append(f"p99 {p99:g} vs {prev['p99_ms']:g} ms")
    if isinstance(qps, (int, float)) and \
            isinstance(prev.get("throughput_qps"), (int, float)) \
            and prev["throughput_qps"] > 0:
        floor = prev["throughput_qps"] * (1.0 - threshold)
        if qps < floor:
            return REGRESS, (
                f"SERVING THROUGHPUT REGRESSION: {qps:g} req/s < "
                f"{floor:g} (previous measured "
                f"{prev['throughput_qps']:g} − {threshold:.0%})")
        msgs.append(f"{qps:g} vs {prev['throughput_qps']:g} req/s")
    return PASS, "serving ok: " + "; ".join(msgs or ["no SLO fields"])


def serving_trajectory(rounds: Sequence[Tuple[int, str,
                                              Optional[Dict]]]) -> str:
    """Serving-SLO series: p50/p99/throughput per round, shed and
    compile-miss evidence next to the ok verdict."""
    lines = ["serving trajectory (SERVING_r*.json + BENCH_SERVING.json)",
             "========================================================="]
    if not rounds:
        return "\n".join(lines + ["(no serving artifacts found)"]) + "\n"
    cols = ("round", "ok", "p50 ms", "p99 ms", "req/s", "shed",
            "miss>warm", "measured", "metric")
    rows = []
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "-", "-", "-", "-", "-", "-", "-",
                         f"<unparseable: {os.path.basename(path)}>"))
            continue
        rows.append((
            f"r{n:02d}", _fmt(bool(rec.get("ok"))),
            _fmt(rec.get("p50_ms")), _fmt(rec.get("p99_ms")),
            _fmt(rec.get("throughput_qps")), _fmt(rec.get("shed")),
            _fmt(rec.get("compile_misses_after_warmup")),
            _fmt(rec.get("measured")) if "measured" in rec else "-",
            normalize_metric(rec.get("metric", "serving"))))
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    p99s = [rec["p99_ms"] for _, _, rec in rounds
            if rec is not None
            and isinstance(rec.get("p99_ms"), (int, float))]
    if p99s:
        lines.append(
            f"p99 across rounds: median {percentile(p99s, 50):.4g} ms, "
            f"p90 {percentile(p99s, 90):.4g} ms over {len(p99s)} "
            f"round(s)")
    return "\n".join(lines) + "\n"


def load_ann(path: str) -> Optional[Dict]:
    """Flat ANN speed/recall frontier record (benchmarks/bench_ann.py):
    unwraps the driver's envelope like :func:`load_serving`. A record
    must carry an ``ok`` verdict or a frontier to count."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed")
    if isinstance(rec, dict) and ("ok" in rec or "frontier" in rec):
        merged = dict(data)
        merged.update(rec)
        return merged
    if "ok" in data or "frontier" in data:
        return data
    return None


def collect_ann(directory: str) -> List[Tuple[int, str, Optional[Dict]]]:
    """(round, path, record) for every ANN_r*.json, in round order,
    plus the bare BENCH_ANN.json (when present) as the NEWEST entry —
    same convention as :func:`collect_serving`."""
    out = []
    for path in glob.glob(os.path.join(directory, ANN_GLOB)):
        m = re.search(r"ANN_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_ann(path)))
    out.sort(key=lambda t: t[0])
    bare = os.path.join(directory, ANN_NAME)
    if os.path.exists(bare):
        n = (out[-1][0] + 1) if out else 1
        out.append((n, bare, load_ann(bare)))
    return out


def _ann_best_recall(rec: Dict) -> Optional[float]:
    frontier = rec.get("frontier")
    if not isinstance(frontier, list):
        return None
    rs = [p.get("recall_at_k") for p in frontier
          if isinstance(p, dict)
          and isinstance(p.get("recall_at_k"), (int, float))]
    return max(rs) if rs else None


def _ann_fine_scan_check(rec: Dict):
    """(error, best_overread) for a round's fine-scan evidence: every
    frontier point the chooser scheduled list-major must realize the
    recorded ``gather_overread`` win (modeled stream bytes ≤ gather
    bytes / overread), and ``best_overread`` is the round's largest
    such win (None when the round predates the fine-scan columns)."""
    best = None
    for p in rec.get("frontier", []) or []:
        if not isinstance(p, dict) or p.get("fine_scan") != "list":
            continue
        sb = p.get("model_stream_bytes")
        gb = p.get("model_gather_bytes")
        ovr = p.get("gather_overread")
        if not all(isinstance(v, (int, float)) and v > 0
                   for v in (sb, gb, ovr)):
            continue
        if sb > gb / ovr * 1.001:
            return (
                f"ANN FINE-SCAN BYTES VIOLATION: frontier point "
                f"n_lists={p.get('n_lists')} n_probes="
                f"{p.get('n_probes')} chose the list-major schedule "
                f"but its modeled stream bytes {sb:g} exceed "
                f"gather/overread = {gb / ovr:g} — the artifact "
                f"records an overread win the schedule does not "
                f"realize"), None
        best = ovr if best is None else max(best, ovr)
    return None, best


def _ann_diffuse_rerun(rec: Dict) -> Tuple[Optional[str],
                                           Optional[float]]:
    """Min certificate exact-rerun fraction among the round's
    diffuse-Gaussian PQ frontier points that reach the recall floor.
    Returns ``(error, frac)``: ``error`` is set when diffuse points
    exist but none reach the floor; ``(None, None)`` means the round
    carries no diffuse points (a pre-ISSUE-19 artifact — the gate
    skips rather than invents a verdict)."""
    pq = rec.get("pq") or {}
    pts = [p for p in pq.get("frontier") or []
           if isinstance(p, dict) and p.get("dist") == "diffuse"]
    if not pts:
        return None, None
    floor = rec.get("recall_floor", 0.95)
    at_floor = [p["cert_rerun_frac"] for p in pts
                if isinstance(p.get("recall_at_k"), (int, float))
                and p["recall_at_k"] >= floor
                and isinstance(p.get("cert_rerun_frac"),
                               (int, float))]
    if not at_floor:
        return ("ANN PQ DIFFUSE RECALL VIOLATION: no diffuse-Gaussian "
                f"PQ frontier point reaches the recall floor {floor:g}"
                " — the compressed tier cannot serve worst-case data "
                "at the promised quality"), None
    return None, float(min(at_floor))


def check_ann(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
              threshold: float = DEFAULT_THRESHOLD) -> Tuple[str, str]:
    """Gate the ANN speed/recall frontier (BENCH_ANN / ANN_r*):

    - the newest parseable round must be ``ok``;
    - degraded ROUND files (nonzero resilience degradations) SKIP —
      outage evidence is history, never a gate; but a degraded NAMED
      artifact (the bare ``BENCH_ANN.json``) REGRESSES — committed
      baseline evidence must never be an outage round (the refresh
      path refuses to write it; one landing anyway is a bug, not
      history);
    - **recall floor**: the frontier's best recall@k must reach the
      artifact's own ``recall_floor`` (default 0.95) — recall is
      platform-independent math, so this gates modeled rounds too;
    - **degenerate-exact invariant**: the ``n_probes = n_lists`` sweep
      point must have matched the brute-force oracle's id sets
      (``degenerate_exact: true``);
    - **fine-scan schedule** (ISSUE 14): list-major frontier points
      must realize the recorded ``gather_overread`` win (modeled
      stream ≤ gather/overread), and the round's best overread win
      must not fall more than ``ANN_OVERREAD_SLACK`` below the
      previous comparable round's;
    - **PQ diffuse rerun** (ISSUE 19): among diffuse-Gaussian PQ
      frontier points, at least one must reach the recall floor and
      the min ``cert_rerun_frac`` there must be ≤ ``PQ_RERUN_CEIL``,
      and must not rise more than ``PQ_RERUN_SLACK`` absolute vs the
      previous comparable round (rounds without diffuse points skip
      this gate);
    - **recall trend**: best recall must not drop more than
      ``ANN_RECALL_SLACK`` absolute vs the previous comparable round;
    - **speed trend**: only MEASURED rounds gate search time — when the
      newest and a previous measured round both carry ``search_ms`` at
      the floor-recall point, it must not grow past ``threshold``
      (modeled rounds are never speed-gated)."""
    newest, newest_path = None, None
    for _, path, rec in reversed(rounds):
        if rec is not None:
            newest, newest_path = rec, path
            break
    if newest is None:
        return SKIP, "no ANN artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest ANN round skipped"
    rd = newest.get("resilience_degradations")
    degraded = (isinstance(rd, (int, float)) and rd > 0) \
        or bool(newest.get("degraded"))
    if degraded:
        if newest_path is not None and os.path.basename(
                newest_path) == ANN_NAME:
            return REGRESS, (
                f"ANN NAMED-ARTIFACT DEGRADED: {ANN_NAME} is stamped "
                f"degraded"
                + (f" ({rd:g} resilience degradation step(s))"
                   if isinstance(rd, (int, float)) and rd > 0 else "")
                + " — committed baseline evidence must never be an "
                  "outage round; regenerate it clean "
                  "(benchmarks/bench_ann.py refuses degraded "
                  "overwrites)")
        return SKIP, (
            f"latest ANN round is degraded"
            + (f" ({rd:g} degradation step(s))"
               if isinstance(rd, (int, float)) and rd > 0 else "")
            + " — a degraded run is history, never gated and never "
              "baseline material")
    if not newest.get("ok", True):
        return REGRESS, ("latest ANN round failed (ok=false) — the "
                         "ANN tier regressed")
    best = _ann_best_recall(newest)
    floor = newest.get("recall_floor", 0.95)
    if isinstance(best, (int, float)) and isinstance(floor,
                                                     (int, float)):
        if best < floor:
            return REGRESS, (
                f"ANN RECALL REGRESSION: best recall@k {best:.4f} < "
                f"floor {floor:g} — no swept n_probes reaches the "
                f"recall the frontier promises")
    if "degenerate_exact" in newest and not newest["degenerate_exact"]:
        return REGRESS, (
            "ANN DEGENERATE-EXACT VIOLATION: the n_probes = n_lists "
            "sweep point did not match the brute-force oracle's id "
            "sets — probing everything must be exact search")
    # fine-scan schedule gate (ISSUE 14): wherever the chooser picked
    # the list-major schedule, its modeled bytes must realize the
    # recorded gather_overread win (stream ≤ gather / overread), and
    # the frontier's recorded overread ratio must not silently shrink
    # vs the previous comparable round — the win BENCH_ANN.json exists
    # to capture cannot regress unnoticed.
    fine_err, fine_ovr = _ann_fine_scan_check(newest)
    if fine_err:
        return REGRESS, fine_err
    # PQ diffuse-rerun gate (ISSUE 19): on the diffuse-Gaussian worst
    # case the adaptive certificate + widen rung must keep the
    # exact-rerun fraction at the recall floor under PQ_RERUN_CEIL —
    # this is the regime where the worst-case certificate collapsed
    # to an 83–88% exact-scan rate and evaporated the ADC win.
    rerun_err, rerun = _ann_diffuse_rerun(newest)
    if rerun_err:
        return REGRESS, rerun_err
    if rerun is not None and rerun > PQ_RERUN_CEIL:
        return REGRESS, (
            f"ANN PQ DIFFUSE RERUN VIOLATION: diffuse-Gaussian "
            f"cert_rerun_frac {rerun:g} at the recall floor exceeds "
            f"{PQ_RERUN_CEIL:g} — the certificate falls back to the "
            f"exact scan often enough to erase the compressed tier's "
            f"win")
    prev = None
    for _, _, rec in reversed(rounds[:-1]):
        if (rec is not None and not rec.get("skipped")
                and _ann_best_recall(rec) is not None
                and rec.get("k") == newest.get("k")):
            prev = rec
            break
    msgs = [f"best recall@{newest.get('k', '?')} "
            f"{best:.4f}" if isinstance(best, (int, float))
            else "no recall points"]
    if fine_ovr is not None:
        msgs.append(f"list-major overread {fine_ovr:g}x")
    if rerun is not None:
        msgs.append(f"diffuse rerun {rerun:g}")
    if prev is not None and isinstance(best, (int, float)):
        pbest = _ann_best_recall(prev)
        if pbest is not None and best < pbest - ANN_RECALL_SLACK:
            return REGRESS, (
                f"ANN RECALL TREND REGRESSION: best recall {best:.4f} "
                f"< previous {pbest:.4f} − {ANN_RECALL_SLACK:g}")
        if pbest is not None:
            msgs.append(f"prev {pbest:.4f}")
        _, prev_ovr = _ann_fine_scan_check(prev)
        if (fine_ovr is not None and prev_ovr is not None
                and fine_ovr < prev_ovr * (1.0 - ANN_OVERREAD_SLACK)):
            return REGRESS, (
                f"ANN FINE-SCAN OVERREAD TREND REGRESSION: the newest "
                f"round's best modeled list-major overread win "
                f"{fine_ovr:g}x fell more than "
                f"{ANN_OVERREAD_SLACK:.0%} below the previous "
                f"comparable round's {prev_ovr:g}x — the frontier "
                f"shift the list-major kernel bought is eroding")
        _, prev_rerun = _ann_diffuse_rerun(prev)
        if (rerun is not None and prev_rerun is not None
                and rerun > prev_rerun + PQ_RERUN_SLACK):
            return REGRESS, (
                f"ANN PQ DIFFUSE RERUN TREND REGRESSION: "
                f"diffuse-Gaussian cert_rerun_frac {rerun:g} rose "
                f"more than {PQ_RERUN_SLACK:g} absolute above the "
                f"previous comparable round's {prev_rerun:g} — "
                f"certificate quality on worst-case data is eroding")
    if newest.get("measured") and prev is not None \
            and prev.get("measured"):
        sm, pm = newest.get("search_ms"), prev.get("search_ms")
        if isinstance(sm, (int, float)) and isinstance(pm, (int, float)) \
                and pm > 0:
            ceil = pm * (1.0 + threshold)
            if sm > ceil:
                return REGRESS, (
                    f"ANN SEARCH-TIME REGRESSION: {sm:g} ms > {ceil:g} "
                    f"(previous measured {pm:g} + {threshold:.0%})")
            msgs.append(f"search {sm:g} vs {pm:g} ms")
    elif not newest.get("measured"):
        msgs.append("modeled — not speed-gated")
    return PASS, "ann ok: " + "; ".join(msgs)


def ann_trajectory(rounds: Sequence[Tuple[int, str,
                                          Optional[Dict]]]) -> str:
    """ANN frontier series: best recall, probed fraction at the floor,
    degenerate-exact verdict per round."""
    lines = ["ann trajectory (ANN_r*.json + BENCH_ANN.json)",
             "=============================================="]
    if not rounds:
        return "\n".join(lines + ["(no ANN artifacts found)"]) + "\n"
    cols = ("round", "ok", "best recall", "floor-probe%", "degen",
            "lists", "measured", "metric")
    rows = []
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "-", "-", "-", "-", "-", "-",
                         f"<unparseable: {os.path.basename(path)}>"))
            continue
        best = _ann_best_recall(rec)
        pf = rec.get("probed_frac_at_floor")
        nl = sorted({p.get("n_lists") for p in rec.get("frontier", [])
                     if isinstance(p, dict)})
        rows.append((
            f"r{n:02d}", _fmt(bool(rec.get("ok"))),
            f"{best:.4f}" if isinstance(best, (int, float)) else "-",
            f"{pf * 100:.1f}" if isinstance(pf, (int, float)) else "-",
            _fmt(rec.get("degenerate_exact")),
            ",".join(str(x) for x in nl if x is not None) or "-",
            _fmt(rec.get("measured")) if "measured" in rec else "-",
            normalize_metric(rec.get("metric", "ann"))))
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"


def load_mutation(path: str) -> Optional[Dict]:
    """Flat mixed read/write record (benchmarks/bench_mutation.py):
    unwraps the driver's envelope like :func:`load_serving`. A record
    must carry an ``ok`` verdict, a recall, or a compaction count to
    count."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed")
    keys = ("ok", "recall", "compaction_cycles")
    if isinstance(rec, dict) and any(k in rec for k in keys):
        merged = dict(data)
        merged.update(rec)
        return merged
    if any(k in data for k in keys):
        return data
    return None


def collect_mutation(directory: str
                     ) -> List[Tuple[int, str, Optional[Dict]]]:
    """(round, path, record) for every MUTATION_r*.json, in round
    order, plus the bare BENCH_MUTATION.json (when present) as the
    NEWEST entry — same convention as :func:`collect_serving`."""
    out = []
    for path in glob.glob(os.path.join(directory, MUTATION_GLOB)):
        m = re.search(r"MUTATION_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_mutation(path)))
    out.sort(key=lambda t: t[0])
    bare = os.path.join(directory, MUTATION_NAME)
    if os.path.exists(bare):
        n = (out[-1][0] + 1) if out else 1
        out.append((n, bare, load_mutation(bare)))
    return out


def check_mutation(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
                   threshold: float = DEFAULT_THRESHOLD
                   ) -> Tuple[str, str]:
    """Gate the mutable-index mixed read/write evidence
    (BENCH_MUTATION / MUTATION_r*):

    - the newest parseable round must be ``ok`` (rebuild-oracle recall
      held, every read completed — a broken mutation plane is a
      regression, not a footnote);
    - degraded rounds (nonzero resilience degradations) SKIP;
    - **compaction cycle**: the round must have completed ≥ 1 full
      delta-fill → fold → swap cycle under load — an artifact that
      never folded proved nothing about the tentpole;
    - **recall floor**: quiescent recall vs the from-scratch rebuild
      oracle must reach the artifact's ``recall_floor`` (0.95) —
      platform-independent, so modeled rounds gate too;
    - **speed trend**: only MEASURED rounds gate read p99 / throughput
      (same ±threshold convention as the serving gate)."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no mutation artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest mutation round skipped"
    rd = newest.get("resilience_degradations")
    if isinstance(rd, (int, float)) and rd > 0:
        return SKIP, (
            f"latest mutation round recorded {rd:g} degradation "
            f"step(s) — a degraded run is history, never gated and "
            f"never baseline material")
    if not newest.get("ok", True):
        return REGRESS, ("latest mutation round failed (ok=false) — "
                         "the mutation plane regressed")
    cycles = newest.get("compaction_cycles")
    if isinstance(cycles, (int, float)) and cycles < 1:
        return REGRESS, (
            "MUTATION COMPACTION REGRESSION: the round completed 0 "
            "compaction cycles — the delta never folded, so the "
            "artifact carries no evidence for the fill→fold→swap "
            "contract")
    recall = newest.get("recall")
    floor = newest.get("recall_floor", QUALITY_RECALL_FLOOR)
    if isinstance(recall, (int, float)) and isinstance(floor,
                                                       (int, float)):
        if recall < floor:
            return REGRESS, (
                f"MUTATION RECALL REGRESSION: rebuild-oracle recall "
                f"{recall:.4f} < floor {floor:g} — interleaved "
                f"mutations degraded served answers")
    msgs = [f"recall {recall:.4f}" if isinstance(recall, (int, float))
            else "no recall field",
            f"{cycles:g} compaction cycle(s)"
            if isinstance(cycles, (int, float)) else "no cycle count"]
    if not newest.get("measured"):
        return PASS, ("mutation ok: " + "; ".join(msgs)
                      + " (modeled — not speed-gated)")
    prev = None
    for _, _, rec in reversed(rounds[:-1]):
        if (rec is not None and rec.get("measured")
                and not rec.get("skipped")
                and isinstance(rec.get("p99_ms"), (int, float))):
            prev = rec
            break
    if prev is None:
        return PASS, ("mutation ok: " + "; ".join(msgs)
                      + " (first measured round)")
    p99, pp99 = newest.get("p99_ms"), prev.get("p99_ms")
    if isinstance(p99, (int, float)) and isinstance(pp99, (int, float)):
        ceil = pp99 * (1.0 + threshold)
        if p99 > ceil:
            return REGRESS, (
                f"MUTATION P99 REGRESSION: {p99:g} ms > {ceil:g} "
                f"(previous measured {pp99:g} + {threshold:.0%})")
        msgs.append(f"p99 {p99:g} vs {pp99:g} ms")
    qps, pqps = newest.get("throughput_qps"), prev.get("throughput_qps")
    if isinstance(qps, (int, float)) and isinstance(pqps, (int, float)) \
            and pqps > 0:
        fl = pqps * (1.0 - threshold)
        if qps < fl:
            return REGRESS, (
                f"MUTATION THROUGHPUT REGRESSION: {qps:g} req/s < "
                f"{fl:g} (previous measured {pqps:g} − {threshold:.0%})")
        msgs.append(f"{qps:g} vs {pqps:g} req/s")
    return PASS, "mutation ok: " + "; ".join(msgs)


def mutation_trajectory(rounds: Sequence[Tuple[int, str,
                                               Optional[Dict]]]) -> str:
    """Mixed read/write series: read p99, recall, compaction cycles and
    mid-fold read evidence per round."""
    lines = [
        "mutation trajectory (MUTATION_r*.json + BENCH_MUTATION.json)",
        "============================================================"]
    if not rounds:
        return "\n".join(lines + ["(no mutation artifacts found)"]) \
            + "\n"
    cols = ("round", "ok", "p99 ms", "req/s", "recall", "cycles",
            "in-fold", "measured", "metric")
    rows = []
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "-", "-", "-", "-", "-", "-", "-",
                         f"<unparseable: {os.path.basename(path)}>"))
            continue
        rows.append((
            f"r{n:02d}", _fmt(bool(rec.get("ok"))),
            _fmt(rec.get("p99_ms")), _fmt(rec.get("throughput_qps")),
            _fmt(rec.get("recall")), _fmt(rec.get("compaction_cycles")),
            _fmt(rec.get("reads_during_fold")),
            _fmt(rec.get("measured")) if "measured" in rec else "-",
            normalize_metric(rec.get("metric", "mutation"))))
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"


def load_recovery(path: str) -> Optional[Dict]:
    """Flat durability/recovery record (benchmarks/bench_recovery.py):
    unwraps the driver's envelope like :func:`load_serving`. A record
    must carry an ``ok`` verdict, the zero-acked-loss flag, or a
    recovery time to count."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    rec = data.get("parsed")
    keys = ("ok", "zero_acked_loss", "recovery_ms")
    if isinstance(rec, dict) and any(k in rec for k in keys):
        merged = dict(data)
        merged.update(rec)
        return merged
    if any(k in data for k in keys):
        return data
    return None


def collect_recovery(directory: str
                     ) -> List[Tuple[int, str, Optional[Dict]]]:
    """(round, path, record) for every RECOVERY_r*.json, in round
    order, plus the bare BENCH_RECOVERY.json (when present) as the
    NEWEST entry — same convention as :func:`collect_serving`."""
    out = []
    for path in glob.glob(os.path.join(directory, RECOVERY_GLOB)):
        m = re.search(r"RECOVERY_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        out.append((int(m.group(1)), path, load_recovery(path)))
    out.sort(key=lambda t: t[0])
    bare = os.path.join(directory, RECOVERY_NAME)
    if os.path.exists(bare):
        n = (out[-1][0] + 1) if out else 1
        out.append((n, bare, load_recovery(bare)))
    return out


def check_recovery(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
                   threshold: float = DEFAULT_THRESHOLD
                   ) -> Tuple[str, str]:
    """Gate the durability/crash-recovery evidence (BENCH_RECOVERY /
    RECOVERY_r*):

    - the newest parseable round must be ``ok`` (acked-write contract
      held, recovered state matched the oracle);
    - degraded rounds (nonzero resilience degradations) SKIP;
    - **zero-acked-loss flag**: the round must carry
      ``zero_acked_loss: true`` — a recovery artifact that lost an
      acked write (or stopped stamping the flag) is THE regression
      this plane exists to prevent; platform-independent, so modeled
      rounds gate too;
    - **recovery-time bound**: ``recovery_ms`` must stay within the
      artifact's own ``recovery_ms_bound`` (the bench sets a
      platform-appropriate ceiling — an unbounded recovery breaks the
      restart-SLO story regardless of chip);
    - **speed trend**: only MEASURED rounds gate durable-write
      throughput (same ±threshold convention as the serving gate)."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no recovery artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest recovery round skipped"
    rd = newest.get("resilience_degradations")
    if isinstance(rd, (int, float)) and rd > 0:
        return SKIP, (
            f"latest recovery round recorded {rd:g} degradation "
            f"step(s) — a degraded run is history, never gated and "
            f"never baseline material")
    if not newest.get("ok", True):
        return REGRESS, ("latest recovery round failed (ok=false) — "
                         "the durability plane regressed")
    if newest.get("zero_acked_loss") is not True:
        return REGRESS, (
            "RECOVERY ACKED-LOSS REGRESSION: the round does not carry "
            "zero_acked_loss=true — an acked write was lost (or the "
            "proof stopped being stamped), the exact contract the WAL "
            "exists to keep")
    rms = newest.get("recovery_ms")
    bound = newest.get("recovery_ms_bound")
    if isinstance(rms, (int, float)) and isinstance(bound,
                                                    (int, float)):
        if rms > bound:
            return REGRESS, (
                f"RECOVERY TIME REGRESSION: {rms:g} ms > the "
                f"artifact's own bound {bound:g} ms — checkpoint + "
                f"WAL-tail replay stopped being a bounded restart")
    msgs = [f"recovery {rms:g} ms" if isinstance(rms, (int, float))
            else "no recovery_ms",
            "zero acked loss"]
    ox = newest.get("durable_overhead_x")
    if isinstance(ox, (int, float)):
        msgs.append(f"durable overhead {ox:.2f}x")
    if not newest.get("measured"):
        return PASS, ("recovery ok: " + "; ".join(msgs)
                      + " (modeled — not speed-gated)")
    prev = None
    for _, _, rec in reversed(rounds[:-1]):
        if (rec is not None and rec.get("measured")
                and not rec.get("skipped")
                and isinstance(rec.get("throughput_qps"),
                               (int, float))):
            prev = rec
            break
    qps = newest.get("throughput_qps")
    if prev is not None and isinstance(qps, (int, float)) \
            and prev["throughput_qps"] > 0:
        floor = prev["throughput_qps"] * (1.0 - threshold)
        if qps < floor:
            return REGRESS, (
                f"RECOVERY THROUGHPUT REGRESSION: durable writes "
                f"{qps:g} req/s < {floor:g} (previous measured "
                f"{prev['throughput_qps']:g} − {threshold:.0%})")
        msgs.append(f"{qps:g} vs {prev['throughput_qps']:g} req/s")
    return PASS, "recovery ok: " + "; ".join(msgs)


def recovery_trajectory(rounds: Sequence[Tuple[int, str,
                                               Optional[Dict]]]) -> str:
    """Durability series: recovery time, replayed-record tail,
    durable-write overhead and the zero-acked-loss verdict per round."""
    lines = [
        "recovery trajectory (RECOVERY_r*.json + BENCH_RECOVERY.json)",
        "============================================================"]
    if not rounds:
        return "\n".join(lines + ["(no recovery artifacts found)"]) \
            + "\n"
    cols = ("round", "ok", "0-loss", "rec ms", "replayed", "overhead x",
            "req/s", "measured", "metric")
    rows = []
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "-", "-", "-", "-", "-", "-", "-",
                         f"<unparseable: {os.path.basename(path)}>"))
            continue
        rows.append((
            f"r{n:02d}", _fmt(bool(rec.get("ok"))),
            _fmt(rec.get("zero_acked_loss")),
            _fmt(rec.get("recovery_ms")),
            _fmt(rec.get("replayed_records")),
            _fmt(rec.get("durable_overhead_x")),
            _fmt(rec.get("throughput_qps")),
            _fmt(rec.get("measured")) if "measured" in rec else "-",
            normalize_metric(rec.get("metric", "recovery"))))
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"


def load_drift_ledger(path: str) -> Optional[Dict]:
    """DRIFT_LEDGER.json → {site: [entries...]}; None for a missing or
    unreadable ledger (the no-op case — the gate must not fail repos
    that have never run a drift-recording benchmark)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    entries = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(entries, dict):
        return None
    return {str(k): v for k, v in entries.items() if isinstance(v, list)}


def check_drift(entries: Optional[Dict], band: float = DRIFT_BAND
                ) -> Tuple[str, str]:
    """Gate the model-vs-measured drift ledger.

    Per site, the NEWEST entry wins. Only entries with ``measured:
    true`` (real-hardware measurements) and both ``predicted_seconds``
    and ``measured_seconds`` are gated — modeled-only sites (the CPU
    suite, prediction-side capture_fn records) are evidence of model
    shape, never calibration failures. A gated site whose
    predicted/measured seconds ratio (either direction) exceeds
    ``band`` is flagged: the cost model that ranks tune tables and
    merge strategies is out of calibration there, and the measured
    round must recalibrate it, not just outvote it."""
    if not entries:
        return SKIP, "no drift ledger to gate"
    flagged, gated, modeled_only = [], 0, 0
    for site in sorted(entries):
        hist = [e for e in entries[site] if isinstance(e, dict)]
        if not hist:
            continue
        latest = hist[-1]
        if not latest.get("measured"):
            modeled_only += 1
            continue
        pred = latest.get("predicted_seconds")
        meas = latest.get("measured_seconds")
        if not (isinstance(pred, (int, float))
                and isinstance(meas, (int, float))
                and pred > 0 and meas > 0):
            modeled_only += 1
            continue
        gated += 1
        ratio = max(pred / meas, meas / pred)
        if ratio > band:
            flagged.append(f"{site} ({ratio:.2g}x)")
    if flagged:
        return REGRESS, (
            f"MODEL DRIFT: {len(flagged)} site(s) outside the "
            f"{band:g}x band: {', '.join(flagged)} — the cost model "
            f"is out of calibration; re-tune before trusting modeled "
            f"rankings")
    if gated == 0:
        return PASS, (f"drift ledger has no measured entries "
                      f"({modeled_only} modeled-only site(s) — never "
                      f"drift-gated)")
    return PASS, (f"drift ok: {gated} measured site(s) within the "
                  f"{band:g}x band"
                  + (f"; {modeled_only} modeled-only skipped"
                     if modeled_only else ""))


def load_lint(path: str) -> Optional[Dict]:
    """LINT_REPORT.json, or None when missing/unreadable (the gate
    then SKIPs with a pointer — an unreadable report never passes
    silently as clean)."""
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def check_lint(record: Optional[Dict]) -> Tuple[str, str]:
    """Gate the graftlint report (ISSUE 13): the committed
    LINT_REPORT.json must carry ``ok: true`` and zero unsuppressed
    error findings — a finding either gets FIXED or gets a reasoned
    baseline entry; it never rides along silently. Suppressed counts
    are reported for visibility (a growing baseline is reviewable
    drift, not a gate failure)."""
    if record is None:
        return SKIP, (f"no {LINT_NAME} — run `python tools/"
                      f"graftlint.py --json` to generate it")
    errs = record.get("unsuppressed_errors")
    if not isinstance(errs, int):
        return REGRESS, (f"{LINT_NAME} is malformed (no "
                         f"unsuppressed_errors count) — regenerate it")
    if errs > 0 or not record.get("ok", False):
        by_pass = {name: blk.get("unsuppressed_errors", 0)
                   for name, blk in (record.get("passes") or {}).items()
                   if isinstance(blk, dict)}
        detail = ", ".join(f"{k}: {v}" for k, v in sorted(
            by_pass.items()) if v)
        return REGRESS, (
            f"LINT: {errs} unsuppressed finding(s)"
            + (f" ({detail})" if detail else "")
            + " — fix them or add reasoned baseline entries "
              "(tools/graftlint_baseline.json)")
    suppressed = record.get("suppressed", 0)
    warnings = record.get("unsuppressed_warnings", 0)
    stale = len(record.get("stale_baseline_entries") or ())
    passes = ", ".join(sorted((record.get("passes") or {})))
    return PASS, (f"lint clean ({passes}; {suppressed} baselined, "
                  f"{warnings} warning(s), {stale} stale baseline "
                  f"entr{'y' if stale == 1 else 'ies'}; commit "
                  f"{record.get('commit', '?')})")


def _git_commit_time(directory: str, ref: str) -> Optional[int]:
    import subprocess

    try:
        r = subprocess.run(
            ["git", "-C", directory, "show", "-s", "--format=%ct", ref],
            capture_output=True, text=True, timeout=10)
        return int(r.stdout.strip().splitlines()[-1]) \
            if r.returncode == 0 and r.stdout.strip() else None
    except Exception:
        return None


def _git_last_touched(directory: str, name: str) -> Optional[int]:
    import subprocess

    try:
        r = subprocess.run(
            ["git", "-C", directory, "log", "-1", "--format=%ct", "--",
             name], capture_output=True, text=True, timeout=10)
        return int(r.stdout.strip()) \
            if r.returncode == 0 and r.stdout.strip() else None
    except Exception:
        return None


def artifact_staleness(directory: str,
                       baseline: Optional[Dict]) -> List[Dict]:
    """Freshness verdict for each :data:`NAMED_ARTIFACTS` file: STALE
    when its last-touching commit predates the commit the last-good
    measurement was taken at — those numbers describe an older code
    state and must not be read as current evidence. Degrades to
    ``unknown`` without git/baseline (never raises)."""
    ref = (baseline or {}).get("git_commit", "")
    ref = str(ref).replace("-dirty", "")
    ref_time = _git_commit_time(directory, ref) if ref else None
    out = []
    for name in NAMED_ARTIFACTS:
        path = os.path.join(directory, name)
        if not os.path.exists(path):
            out.append({"artifact": name, "status": "missing"})
            continue
        touched = _git_last_touched(directory, name)
        if touched is None or ref_time is None:
            out.append({"artifact": name, "status": "unknown"})
            continue
        stale = touched < ref_time
        out.append({
            "artifact": name,
            "status": "STALE" if stale else "current",
            "age_rounds_note": (
                "last touched before the last-good commit — numbers "
                "describe an older code state" if stale else ""),
        })
    return out


def check_regression(record: Optional[Dict], baseline: Optional[Dict],
                     threshold: float = DEFAULT_THRESHOLD
                     ) -> Tuple[str, str]:
    """Gate one candidate record against the baseline.

    Returns (status, message) with status one of PASS / REGRESS /
    MISSING_BASELINE / SKIP. SKIP covers: no candidate, degraded
    candidate, or metric/unit not comparable with the baseline — the
    no-op cases CI must treat as success."""
    if record is None:
        return SKIP, "no new BENCH artifact to gate"
    if record.get("degraded"):
        return SKIP, ("latest artifact is degraded (outage/CPU fallback)"
                      " — not gated")
    rd = record.get("resilience_degradations")
    if isinstance(rd, (int, float)) and rd > 0:
        return SKIP, (
            f"latest artifact recorded {rd:g} resilience degradation "
            f"ladder step(s) — numbers from a degraded run are "
            f"history, never gated and never baseline material")
    value = record.get("value")
    if not isinstance(value, (int, float)):
        return SKIP, "latest artifact has no numeric value"
    if baseline is None:
        return MISSING_BASELINE, (
            f"no {BASELINE_NAME} to gate against (candidate "
            f"{record.get('metric', '?')!r} = {value})")
    base_value = baseline.get("value")
    if not isinstance(base_value, (int, float)) or base_value <= 0:
        return MISSING_BASELINE, f"{BASELINE_NAME} has no usable value"
    if normalize_metric(record.get("metric", "")) != \
            normalize_metric(baseline.get("metric", "")) \
            or record.get("unit") != baseline.get("unit"):
        return SKIP, ("latest artifact measures a different metric/unit "
                      "than the baseline — not comparable")
    unit = record.get("unit", "")
    if higher_is_better(unit):
        floor = base_value * (1.0 - threshold)
        if value < floor:
            return REGRESS, (
                f"REGRESSION: {value:g} {unit} < {floor:g} "
                f"(last good {base_value:g} − {threshold:.0%})")
        return _check_roofline(
            record, baseline, threshold,
            f"ok: {value:g} {unit} vs last good "
            f"{base_value:g} (threshold {threshold:.0%})")
    ceil = base_value * (1.0 + threshold)
    if value > ceil:
        return REGRESS, (
            f"REGRESSION: {value:g} {unit} > {ceil:g} "
            f"(last good {base_value:g} + {threshold:.0%})")
    return _check_roofline(
        record, baseline, threshold,
        f"ok: {value:g} {unit} vs last good {base_value:g} "
        f"(threshold {threshold:.0%})")


def _check_roofline(record: Dict, baseline: Dict, threshold: float,
                    pass_msg: str) -> Tuple[str, str]:
    """Second-stage gate on the ROOFLINE-FRACTION trend: a round whose
    headline GB/s holds can still have lost ground against what the
    hardware allows (e.g. the cost model's bytes shrank — less work per
    second at the same rate). Only fires when BOTH records carry a
    numeric roofline_frac; seconds-only history stays gateable by the
    headline alone."""
    rf = record.get("roofline_frac")
    base_rf = baseline.get("roofline_frac")
    if (isinstance(rf, (int, float))
            and isinstance(base_rf, (int, float)) and base_rf > 0):
        floor = base_rf * (1.0 - threshold)
        if rf < floor:
            return REGRESS, (
                f"ROOFLINE REGRESSION: roofline_frac {rf:.3g} < "
                f"{floor:.3g} (last good {base_rf:.3g} − "
                f"{threshold:.0%}) even though the headline holds — "
                f"the chip allows more than this round achieved")
        pass_msg += (f"; roofline_frac {rf:.3g} vs last good "
                     f"{base_rf:.3g}")
    return PASS, pass_msg


def _fmt(v, nd=4) -> str:
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, (int, float)):
        return f"{v:.{nd}g}"
    return "-" if v is None else str(v)


def trajectory(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
               baseline: Optional[Dict]) -> str:
    """Human trajectory: one row per round (headline value, p1/p3
    sub-series, commit, degraded) + roofline columns when present."""
    lines = ["perf trajectory (BENCH_r*.json)",
             "================================"]
    cols = ("round", "value", "unit", "p1 GB/s", "p3 GB/s", "p3 ms",
            "%roof", "bound", "degraded", "commit", "metric")
    rows = []
    any_cost = any(rec and any(f in rec for f in COST_FIELDS)
                   for _, _, rec in rounds)
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "?", "-", "-", "-", "-", "-", "-",
                         "-", "-", f"<unparseable: {os.path.basename(path)}>"))
            continue
        rf = rec.get("roofline_frac")
        rows.append((
            f"r{n:02d}", _fmt(rec.get("value")), rec.get("unit", "-"),
            _fmt(rec.get("p1_gbps")), _fmt(rec.get("p3_gbps")),
            _fmt(rec.get("p3_ms")),
            f"{rf * 100:.1f}" if isinstance(rf, (int, float)) else "-",
            _fmt(rec.get("bound")), _fmt(bool(rec.get("degraded"))),
            rec.get("git_commit", "-"),
            normalize_metric(rec.get("metric", "?"))))
    if baseline is not None:
        rf = baseline.get("roofline_frac")
        rows.append((
            "LAST_GOOD", _fmt(baseline.get("value")),
            baseline.get("unit", "-"), _fmt(baseline.get("p1_gbps")),
            _fmt(baseline.get("p3_gbps")), _fmt(baseline.get("p3_ms")),
            f"{rf * 100:.1f}" if isinstance(rf, (int, float)) else "-",
            _fmt(baseline.get("bound")), "-",
            baseline.get("git_commit", "-"),
            normalize_metric(baseline.get("metric", "?"))))
    if not rows:
        return "\n".join(lines + ["(no BENCH_r*.json artifacts found)"]) + "\n"
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    if not any_cost:
        lines.append("")
        lines.append("(no cost-model fields yet — artifacts produced "
                     "before the roofline profiler carry only seconds; "
                     "the next measurement round fills flops/bytes/%roof)")
    return "\n".join(lines) + "\n"


def multichip_trajectory(rounds: Sequence[Tuple[int, str,
                                                Optional[Dict]]]) -> str:
    """Multichip series: dryrun verdicts for the bare early rounds,
    sharded-KNN throughput + best busbw fraction once artifacts carry
    them (benchmarks/bench_sharded.py)."""
    lines = ["multichip trajectory (MULTICHIP_r*.json)",
             "========================================="]
    if not rounds:
        return "\n".join(lines + ["(no MULTICHIP_r*.json artifacts "
                                  "found)"]) + "\n"
    cols = ("round", "devices", "ok", "value", "unit", "busbw%",
            "measured", "metric")
    rows = []
    for n, path, rec in rounds:
        if rec is None:
            rows.append((f"r{n:02d}", "-", "-", "-", "-", "-", "-",
                         f"<unparseable: {os.path.basename(path)}>"))
            continue
        bw = _best_busbw(rec)
        rows.append((
            f"r{n:02d}", _fmt(rec.get("n_devices")),
            _fmt(bool(rec.get("ok"))), _fmt(rec.get("value")),
            rec.get("unit", "-"),
            f"{bw * 100:.2f}" if isinstance(bw, (int, float)) else "-",
            _fmt(rec.get("measured")) if "measured" in rec else "-",
            normalize_metric(rec.get("metric", "dryrun"))))
    widths = [max(len(c), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"


def check_quantized(records: Sequence[Tuple[str, Optional[Dict]]],
                    ceil: float = QUANTIZED_RATIO_CEIL
                    ) -> Tuple[str, str]:
    """Gate the quantized-index-streaming evidence across artifact
    families. ``records`` is [(family, newest record)] — each record
    that carries a ``"quantized"`` block must have ``ok: true``
    (id-parity int8-vs-f32 held) and its modeled bytes ratio
    (``quantized_y_ratio`` for the fused stream,
    ``quantized_gather_ratio`` for the IVF probe gather) ≤ ``ceil``.
    Records carrying a ``"pq"`` block (the IVF-PQ compressed tier —
    benchmarks/bench_ann.py) are additionally gated at the much
    tighter :data:`PQ_RATIO_CEIL`: ``pq_bytes_ratio`` ≤ 0.10× of the
    f32 slab stream AND the id-parity-after-rescore ``ok`` flag —
    AND-ed into the same verdict. Families without the block are
    noted; when NO family carries one the gate SKIPs (pass-or-no-op —
    pre-quantization artifact sets)."""
    checked, missing = [], []
    for family, rec in records:
        pq = rec.get("pq") if isinstance(rec, dict) else None
        if isinstance(pq, dict):
            if not pq.get("ok"):
                detail = pq.get("error") or (
                    "rescored PQ ids diverged from the flat scan, or "
                    "no point met the recall floor at the ratio ceil")
                return REGRESS, (
                    f"QUANTIZED REGRESSION [{family}/pq]: "
                    f"id-parity-after-rescore ok={pq.get('ok')} "
                    f"({detail})")
            pratio = pq.get("pq_bytes_ratio")
            if not isinstance(pratio, (int, float)):
                return REGRESS, (
                    f"QUANTIZED REGRESSION [{family}/pq]: pq block "
                    f"carries no pq_bytes_ratio")
            if pratio > PQ_RATIO_CEIL:
                return REGRESS, (
                    f"QUANTIZED REGRESSION [{family}/pq]: modeled "
                    f"codes-stream ratio {pratio:.4f} > "
                    f"{PQ_RATIO_CEIL:g}× the f32 slab — the "
                    f"compressed tier stopped paying for itself")
            checked.append(f"{family}/pq={pratio:.4f}")
        q = rec.get("quantized") if isinstance(rec, dict) else None
        if not isinstance(q, dict):
            if not isinstance(pq, dict):
                missing.append(family)
            continue
        if not q.get("ok"):
            detail = q.get("error") or ("int8 ids diverged from the "
                                        "f32 oracle")
            return REGRESS, (
                f"QUANTIZED REGRESSION [{family}]: id-parity ok="
                f"{q.get('ok')} ({detail})")
        ratio = None
        for key in ("quantized_y_ratio", "quantized_gather_ratio"):
            if isinstance(q.get(key), (int, float)):
                ratio = float(q[key])
                break
        if ratio is None:
            return REGRESS, (
                f"QUANTIZED REGRESSION [{family}]: block carries no "
                f"modeled bytes ratio")
        if ratio > ceil:
            return REGRESS, (
                f"QUANTIZED REGRESSION [{family}]: modeled streamed-"
                f"bytes ratio {ratio:.3f} > {ceil:g}× the bf16/f32 "
                f"baseline — the int8 path stopped paying for itself")
        checked.append(f"{family}={ratio:.3f}")
    if not checked:
        return SKIP, "no artifact carries a quantized block — not gated"
    note = f" (no block: {', '.join(missing)})" if missing else ""
    return PASS, ("int8 ratios " + ", ".join(checked)
                  + f" ≤ {ceil:g}, id-parity ok" + note)


def check_quality(records: Sequence[Tuple[str, Optional[Dict]]],
                  floor: float = QUALITY_RECALL_FLOOR
                  ) -> Tuple[str, str]:
    """Gate the quality-telemetry evidence across artifact families.

    ``records`` is [(family, newest record)]. Each record that carries
    a ``"quality"`` block must have a numeric ``fixup_rate`` (the
    certificate/fixup counters actually flowed — a block without it
    means the telemetry plane silently broke), and any recall the
    block carries (``shadow_recall`` from the online sampler,
    ``offline_recall`` from the ANN frontier) must reach ``floor``.
    Families without a block are noted; when NO family carries one the
    gate SKIPs (pass-or-no-op — pre-quality artifact sets). Quality is
    platform-independent math, so modeled rounds gate too — only
    SPEED is ever measured-only."""
    checked, missing = [], []
    for family, rec in records:
        q = rec.get("quality") if isinstance(rec, dict) else None
        if not isinstance(q, dict):
            missing.append(family)
            continue
        if not isinstance(q.get("fixup_rate"), (int, float)):
            return REGRESS, (
                f"QUALITY REGRESSION [{family}]: quality block carries "
                f"no fixup_rate — the certificate/fixup counters "
                f"stopped flowing into the artifact")
        notes = [f"fixup_rate={q['fixup_rate']:g}"]
        for key in ("shadow_recall", "offline_recall"):
            r = q.get(key)
            if r is None:
                continue
            if not isinstance(r, (int, float)):
                return REGRESS, (
                    f"QUALITY REGRESSION [{family}]: {key} is "
                    f"non-numeric ({r!r})")
            if r < floor:
                return REGRESS, (
                    f"QUALITY REGRESSION [{family}]: {key} "
                    f"{r:.4f} < floor {floor:g} — served answers "
                    f"degraded below the gated recall")
            notes.append(f"{key}={r:.4f}")
        checked.append(f"{family}({', '.join(notes)})")
    if not checked:
        return SKIP, "no artifact carries a quality block — not gated"
    note = f" (no block: {', '.join(missing)})" if missing else ""
    return PASS, "quality ok: " + "; ".join(checked) + note


#: availability floor for the serving SLO gate: an ok round that served
#: less than this fraction of admitted requests is a regression.
SLO_AVAILABILITY_FLOOR = 0.99


def check_slo(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
              floor: float = SLO_AVAILABILITY_FLOOR
              ) -> Tuple[str, str]:
    """Gate the serving SLO block (ISSUE 16).

    The newest parseable serving round must carry an ``"slo"`` block
    (MISSING_BASELINE without one — the artifact predates the SLO
    plane, regenerate it); degraded rounds SKIP (outage evidence is
    history, never a gate). On an ok round:

    - run-cumulative ``availability`` must reach ``floor`` (0.99 —
      admitted requests that shed/expired/errored ate more than the
      availability budget);
    - no page-severity fast-burn alert may have fired
      (``fast_burn_alerts == 0``) — an ok round that still tripped the
      pager means the burn thresholds and the serving path disagree
      about health, which is exactly what this gate exists to catch.
      On MODELED (off-TPU) rounds latency burns are excluded: latency
      is speed evidence and CPU wall clock is never chip evidence —
      the same measured-only rule every speed gate here follows."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no serving artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest serving round skipped"
    rd = newest.get("resilience_degradations")
    if isinstance(rd, (int, float)) and rd > 0:
        return SKIP, (
            f"latest serving round recorded {rd:g} degradation "
            f"step(s) — a degraded run is history, never gated")
    slo = newest.get("slo")
    if not isinstance(slo, dict):
        return MISSING_BASELINE, (
            "latest serving round carries no slo block — regenerate "
            "BENCH_SERVING.json (benchmarks/bench_serving.py)")
    if not newest.get("ok", True):
        return SKIP, ("latest serving round failed (ok=false) — the "
                      "[serving] gate owns that regression")
    avail = slo.get("availability")
    if avail is None:
        return SKIP, "slo block has no availability evidence (no traffic)"
    if not isinstance(avail, (int, float)):
        return REGRESS, (
            f"SLO REGRESSION: availability is non-numeric ({avail!r})")
    if avail < floor:
        return REGRESS, (
            f"SLO REGRESSION: availability {avail:.4f} < floor "
            f"{floor:g} ({slo.get('bad_requests', '?')} bad of "
            f"{slo.get('total_requests', '?')} requests)")
    burns = slo.get("fast_burn_alerts")
    note = ""
    if isinstance(burns, (int, float)) and burns > 0:
        by_slo = slo.get("fast_burn_by_slo")
        if newest.get("measured") or not isinstance(by_slo, dict):
            gated = {"all": burns} if not isinstance(by_slo, dict) \
                else by_slo
        else:
            gated = {k: v for k, v in by_slo.items()
                     if k != "latency_p99" and v > 0}
        if gated:
            return REGRESS, (
                f"SLO REGRESSION: page-severity burn alert(s) fired "
                f"during an ok round ({gated}) — the pager and the "
                f"serving path disagree about health")
        note = (f" (latency fast-burn(s) {by_slo} not gated on a "
                f"modeled round — CPU wall clock is not chip evidence)")
    return PASS, (f"slo ok: availability {avail:.4f} ≥ {floor:g} "
                  f"over {slo.get('total_requests', '?')} request(s)"
                  + note)


#: blackbox overhead ceiling: the crash-durable recorder may cost at
#: most this fraction of total client request wall time.
BLACKBOX_OVERHEAD_CEILING = 0.01


def check_blackbox(rounds: Sequence[Tuple[int, str, Optional[Dict]]],
                   ceiling: float = BLACKBOX_OVERHEAD_CEILING
                   ) -> Tuple[str, str]:
    """Gate the serving blackbox block (ISSUE 17).

    The newest parseable serving round must carry a ``"blackbox"``
    block (MISSING_BASELINE without one — the artifact predates the
    forensics plane, regenerate it). On an ok round the recorder's
    measured ``overhead_frac`` (cumulative mmap-append seconds over
    total client request wall time) must stay under ``ceiling`` (1%) —
    a flight recorder that taxes the requests it exists to explain is
    a regression, not a feature."""
    newest = None
    for _, _, rec in reversed(rounds):
        if rec is not None:
            newest = rec
            break
    if newest is None:
        return SKIP, "no serving artifact to gate"
    if newest.get("skipped"):
        return SKIP, "latest serving round skipped"
    bb = newest.get("blackbox")
    if not isinstance(bb, dict):
        return MISSING_BASELINE, (
            "latest serving round carries no blackbox block — "
            "regenerate BENCH_SERVING.json "
            "(benchmarks/bench_serving.py)")
    if not newest.get("ok", True):
        return SKIP, ("latest serving round failed (ok=false) — the "
                      "[serving] gate owns that regression")
    frac = bb.get("overhead_frac")
    if frac is None:
        return SKIP, "blackbox block has no overhead evidence (no traffic)"
    if not isinstance(frac, (int, float)):
        return REGRESS, (
            f"BLACKBOX REGRESSION: overhead_frac is non-numeric "
            f"({frac!r})")
    if frac >= ceiling:
        return REGRESS, (
            f"BLACKBOX REGRESSION: record overhead {frac:.4%} of "
            f"request wall time ≥ ceiling {ceiling:.0%} "
            f"({bb.get('records', '?')} record(s), "
            f"{bb.get('append_seconds', '?')}s appending)")
    return PASS, (f"blackbox ok: overhead {frac:.4%} < {ceiling:.0%} "
                  f"over {bb.get('records', '?')} record(s), "
                  f"{bb.get('bytes_written', '?')} bytes")


def staleness_section(entries: List[Dict]) -> str:
    lines = ["named artifacts (freshness vs the last-good commit)",
             "---------------------------------------------------"]
    for e in entries:
        note = e.get("age_rounds_note") or ""
        lines.append(f"{e['artifact']:<24} {e['status']}"
                     + (f" — {note}" if note else ""))
    return "\n".join(lines) + "\n"


def main(argv: Sequence[str] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default=_REPO_ROOT,
                   help="directory holding BENCH_*.json (default: repo root)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <dir>/{BASELINE_NAME})")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="relative regression threshold (default 0.15)")
    p.add_argument("--check", action="store_true",
                   help="gate the newest non-degraded round against the "
                        "baseline; exit 1 on regression, 2 on missing "
                        "baseline, 0 otherwise")
    p.add_argument("--drift-ledger", default=None,
                   help=f"drift ledger file (default: "
                        f"<dir>/{DRIFT_LEDGER_NAME})")
    p.add_argument("--drift-band", type=float, default=DRIFT_BAND,
                   help="flag sites whose predicted/measured seconds "
                        "ratio exceeds this factor either way "
                        f"(default {DRIFT_BAND:g}; measured entries "
                        "only — modeled rounds are never drift-gated)")
    p.add_argument("--json", action="store_true",
                   help="emit the trajectory as JSON instead of a table")
    args = p.parse_args(argv)

    rounds = collect_rounds(args.dir)
    mrounds = collect_multichip(args.dir)
    srounds = collect_serving(args.dir)
    arounds = collect_ann(args.dir)
    murounds = collect_mutation(args.dir)
    rrounds = collect_recovery(args.dir)
    baseline_path = args.baseline or os.path.join(args.dir, BASELINE_NAME)
    baseline = load_record(baseline_path)
    stale = artifact_staleness(args.dir, baseline)

    if args.check:
        # newest round wins; older rounds are history, not candidates
        candidate = None
        for _, _, rec in reversed(rounds):
            if rec is not None:
                candidate = rec
                break
        status, msg = check_regression(candidate, baseline, args.threshold)
        if candidate is not None and "drift_checked" in candidate:
            msg += (" [drift-checked round]" if candidate["drift_checked"]
                    else " [modeled round — not drift-calibrated]")
        print(f"bench_report --check: {status}: {msg}")
        mstatus, mmsg = check_multichip(mrounds, args.threshold)
        print(f"bench_report --check [multichip]: {mstatus}: {mmsg}")
        sstatus, smsg = check_serving(srounds, args.threshold)
        print(f"bench_report --check [serving]: {sstatus}: {smsg}")
        astatus, amsg = check_ann(arounds, args.threshold)
        print(f"bench_report --check [ann]: {astatus}: {amsg}")
        mustatus, mumsg = check_mutation(murounds, args.threshold)
        print(f"bench_report --check [mutation]: {mustatus}: {mumsg}")
        rstatus, rmsg = check_recovery(rrounds, args.threshold)
        print(f"bench_report --check [recovery]: {rstatus}: {rmsg}")
        # multichip: the bare benchmark artifact (written by
        # benchmarks/bench_sharded.py) is the freshest carrier of the
        # quantized block — driver rounds lag it by one round
        newest_m = load_multichip(
            os.path.join(args.dir, "MULTICHIP_SHARDED.json"))
        if newest_m is None:
            newest_m = next((rec for _, _, rec in reversed(mrounds)
                             if rec is not None), None)
        newest_a = next((rec for _, _, rec in reversed(arounds)
                         if rec is not None), None)
        qstatus, qmsg = check_quantized(
            [("bench", candidate), ("multichip", newest_m),
             ("ann", newest_a)])
        print(f"bench_report --check [quantized]: {qstatus}: {qmsg}")
        # quality: every family's newest artifact — blocks are stamped
        # by benchmark.Fixture.run / the bench writers (ISSUE 10)
        newest_s = next((rec for _, _, rec in reversed(srounds)
                         if rec is not None), None)
        newest_mu = next((rec for _, _, rec in reversed(murounds)
                          if rec is not None), None)
        qlstatus, qlmsg = check_quality(
            [("bench", candidate), ("multichip", newest_m),
             ("serving", newest_s), ("ann", newest_a),
             ("mutation", newest_mu)])
        print(f"bench_report --check [quality]: {qlstatus}: {qlmsg}")
        slstatus, slmsg = check_slo(srounds)
        print(f"bench_report --check [slo]: {slstatus}: {slmsg}")
        bbstatus, bbmsg = check_blackbox(srounds)
        print(f"bench_report --check [blackbox]: {bbstatus}: {bbmsg}")
        ledger_path = args.drift_ledger or os.path.join(
            args.dir, DRIFT_LEDGER_NAME)
        dstatus, dmsg = check_drift(load_drift_ledger(ledger_path),
                                    args.drift_band)
        print(f"bench_report --check [drift]: {dstatus}: {dmsg}")
        lstatus, lmsg = check_lint(
            load_lint(os.path.join(args.dir, LINT_NAME)))
        print(f"bench_report --check [lint]: {lstatus}: {lmsg}")
        for e in stale:
            if e.get("status") == "STALE":
                print(f"bench_report --check: note: {e['artifact']} is "
                      f"STALE ({e['age_rounds_note']})")
        codes = {PASS: 0, SKIP: 0, REGRESS: 1, MISSING_BASELINE: 2}
        # regression in ANY trend fails; missing baseline only when
        # nothing regressed
        rcs = (codes[status], codes[mstatus], codes[sstatus],
               codes[astatus], codes[mustatus], codes[rstatus],
               codes[qstatus], codes[qlstatus], codes[slstatus],
               codes[bbstatus], codes[dstatus], codes[lstatus])
        return 1 if 1 in rcs else max(rcs)

    if args.json:
        payload = {
            "rounds": [{"round": n, "path": os.path.basename(path),
                        "record": rec} for n, path, rec in rounds],
            "multichip_rounds": [
                {"round": n, "path": os.path.basename(path),
                 "record": rec} for n, path, rec in mrounds],
            "serving_rounds": [
                {"round": n, "path": os.path.basename(path),
                 "record": rec} for n, path, rec in srounds],
            "ann_rounds": [
                {"round": n, "path": os.path.basename(path),
                 "record": rec} for n, path, rec in arounds],
            "mutation_rounds": [
                {"round": n, "path": os.path.basename(path),
                 "record": rec} for n, path, rec in murounds],
            "recovery_rounds": [
                {"round": n, "path": os.path.basename(path),
                 "record": rec} for n, path, rec in rrounds],
            "named_artifacts": stale,
            "lint": load_lint(os.path.join(args.dir, LINT_NAME)),
            "baseline": baseline,
            "drift_ledger": load_drift_ledger(
                args.drift_ledger
                or os.path.join(args.dir, DRIFT_LEDGER_NAME)),
        }
        print(json.dumps(payload, indent=1, sort_keys=True, default=str))
        return 0

    sys.stdout.write(trajectory(rounds, baseline))
    sys.stdout.write("\n")
    sys.stdout.write(multichip_trajectory(mrounds))
    sys.stdout.write("\n")
    sys.stdout.write(serving_trajectory(srounds))
    sys.stdout.write("\n")
    sys.stdout.write(ann_trajectory(arounds))
    sys.stdout.write("\n")
    sys.stdout.write(mutation_trajectory(murounds))
    sys.stdout.write("\n")
    sys.stdout.write(recovery_trajectory(rrounds))
    sys.stdout.write("\n")
    sys.stdout.write(staleness_section(stale))
    return 0


if __name__ == "__main__":
    sys.exit(main())
