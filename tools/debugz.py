"""debugz: the live HTTP front door over the observability planes.

Borg-style read-only debug endpoints served from a daemon thread inside
the process (stdlib ``http.server`` — no dependency, no framework):

- ``/statusz``   — the human health snapshot (:mod:`tools.statusz`)
- ``/metricsz``  — Prometheus text exposition (scrape target)
- ``/explainz``  — the explain-record ring as JSON
  (``?outcome=ok|error|deadline`` filters, ``?limit=N`` truncates)
- ``/flightz``   — the flight ring as a Perfetto-loadable trace JSON
- ``/healthz``   — 200 ``ok`` normally; 503 ``burning`` while the SLO
  engine has a page-severity burn alert active (a load balancer's
  drain signal)
- ``/stackz``    — live thread dump (every Python thread's stack with
  blocked-at lock-site annotations — what the hang watchdog writes
  into the blackbox, readable on demand)
- ``/crashz``    — the PRIOR run's postmortem reconstruction when the
  engine booted over an epilogue-less blackbox (verdict, final
  metrics snapshot, in-flight table, event tail); ``{"verdict":
  "none"}`` after a clean predecessor

Wire it through the engine (``ServingEngine(debug_port=0)`` or the
``RAFT_TPU_DEBUGZ_PORT`` env knob — port 0 binds an ephemeral port,
read it back from :attr:`DebugzServer.port`) or standalone::

    srv = DebugzServer(engine=eng, port=9090).start()
    ...
    srv.stop()

Binds 127.0.0.1 by default: these pages expose index geometry and
query timings — keep them off the open network unless you front them
with real auth. Every handler is read-only and never raises: a broken
subsystem renders as an error note in the page body, because this
server exists to be read WHILE things are broken.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class _Handler(BaseHTTPRequestHandler):
    # the server instance injects itself; class attr keeps mypy quiet
    debugz: "DebugzServer"

    # quiet: one log line per scrape would drown the process log
    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def _send(self, status: int, body: str,
              ctype: str = "text/plain; charset=utf-8") -> None:
        payload = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-write; nothing to clean up

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        url = urlparse(self.path)
        route = url.path.rstrip("/") or "/"
        try:
            if route == "/statusz" or route == "/":
                self._statusz()
            elif route == "/metricsz":
                self._metricsz()
            elif route == "/explainz":
                self._explainz(parse_qs(url.query))
            elif route == "/flightz":
                self._flightz()
            elif route == "/healthz":
                self._healthz()
            elif route == "/stackz":
                self._stackz()
            elif route == "/crashz":
                self._crashz()
            else:
                self._send(404, "not found: %s\n" % route)
        except Exception as e:  # read-only page: render, don't raise
            self._send(500, "debugz handler error: %r\n" % (e,))

    def _statusz(self) -> None:
        from tools.statusz import render_statusz

        self._send(200, render_statusz(engine=self.debugz.engine))

    def _metricsz(self) -> None:
        from raft_tpu.observability.exporters import export_prometheus

        self._send(200, export_prometheus(),
                   ctype="text/plain; version=0.0.4; charset=utf-8")

    def _explainz(self, qs) -> None:
        from raft_tpu.observability.explain import explain_records

        outcome = (qs.get("outcome") or [None])[0]
        try:
            limit = int((qs.get("limit") or [64])[0])
        except (TypeError, ValueError):
            limit = 64
        records = explain_records(outcome=outcome, limit=limit)
        self._send(200, json.dumps({"records": records}, default=str,
                                   indent=2) + "\n",
                   ctype="application/json")

    def _flightz(self) -> None:
        from raft_tpu.observability.exporters import export_perfetto

        self._send(200, json.dumps(export_perfetto()) + "\n",
                   ctype="application/json")

    def _stackz(self) -> None:
        from raft_tpu.observability.watchdog import format_stacks

        self._send(200, format_stacks() + "\n")

    def _crashz(self) -> None:
        eng = self.debugz.engine
        report = (getattr(eng, "crash_report", None)
                  if eng is not None else None)
        if report is None:
            report = {"verdict": "none",
                      "note": "no prior-run unclean blackbox detected"}
        self._send(200, json.dumps(report, default=str, indent=2)
                   + "\n", ctype="application/json")

    def _healthz(self) -> None:
        burning = False
        eng = self.debugz.engine
        slo = getattr(eng, "slo", None) if eng is not None else None
        if slo is not None:
            try:
                burning = bool(slo.burning("page"))
            except Exception:
                burning = False
        if burning:
            self._send(503, "burning\n")
        else:
            self._send(200, "ok\n")


class DebugzServer:
    """The debug HTTP server: ThreadingHTTPServer on a daemon thread.
    ``port=0`` binds an ephemeral port (tests); read the bound port
    back from :attr:`port` after :meth:`start`."""

    def __init__(self, engine=None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.engine = engine
        self._requested_port = int(port)
        self._host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port (None before :meth:`start`)."""
        if self._httpd is None:
            return None
        return self._httpd.server_address[1]

    def start(self) -> "DebugzServer":
        if self._httpd is not None:
            return self
        handler = type("_BoundHandler", (_Handler,), {"debugz": self})
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="debugz", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)


def main(argv=None) -> int:
    """Standalone: serve the observability planes of a demo round (or
    just the process registry) until interrupted."""
    import argparse
    import time

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--port", type=int, default=9090)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny CPU serving round first so the "
                         "pages have content")
    args = ap.parse_args(argv)
    engine = None
    if args.demo:
        from tools.statusz import _demo_round

        engine = _demo_round()
    srv = DebugzServer(engine=engine, port=args.port,
                       host=args.host).start()
    print("debugz listening on http://%s:%d  "
          "(/statusz /metricsz /explainz /flightz /healthz /stackz "
          "/crashz)"
          % (args.host, srv.port))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
