"""postmortem: read a dead process's blackbox and say what happened.

The offline half of the forensics plane
(:mod:`raft_tpu.observability.blackbox`): point it at the ring file a
killed / crashed / hung process left behind and it reconstructs —
tolerating the torn tail via per-record CRCs, exactly like WAL
recovery — and prints:

- the **verdict**: ``clean`` (the newest record is the epilogue),
  ``hang`` (the watchdog got a stall dump in before death) or
  ``crash`` (violent death with a healthy batcher — SIGKILL, OOM,
  native crash);
- the run header (pid, wall-clock start), record/torn counts;
- the **final metrics snapshot** (requests, sheds, deadline fails —
  the counters as the process last saw them);
- alerts still **firing** at death, the **in-flight request table**,
  and the newest flight events;
- with ``--trace out.json``, the last-N-seconds timeline as a
  Perfetto/Chrome trace (open at https://ui.perfetto.dev) via the same
  exporter the live ``/flightz`` route uses.

Usage::

    python tools/postmortem.py /var/run/raft/blackbox.bin
    python tools/postmortem.py blackbox.bin --json          # machine view
    python tools/postmortem.py blackbox.bin --trace tail.json --last-s 5

Exit code 0 for ``clean``, 2 for ``crash``/``hang`` (scriptable), 1 on
an unreadable file. The live counterpart is debugz ``/crashz``: on
restart the engine runs this same reconstruction over its
predecessor's file automatically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python tools/postmortem.py`
    sys.path.insert(0, _REPO)


class _ReplayRecorder:
    """Just enough FlightRecorder surface (``events()``) to feed the
    reconstructed event list through ``export_perfetto``."""

    def __init__(self, events: List[Dict]):
        self._events = events

    def events(self) -> List[Dict]:
        return list(self._events)


def _tail_filter(events: List[Dict], last_s: Optional[float]
                 ) -> List[Dict]:
    """Events within ``last_s`` seconds of the newest event's stamp
    (perf_counter clock — relative windows only make sense within one
    run, which is exactly what a blackbox holds)."""
    if not last_s or not events:
        return events
    newest = max(float(e.get("ts") or 0.0) for e in events)
    floor = newest - float(last_s)
    return [e for e in events if float(e.get("ts") or 0.0) >= floor]


def write_trace(report: Dict, out_path: str,
                last_s: Optional[float] = None) -> int:
    """Write the reconstructed last-``last_s``-seconds timeline as
    Perfetto JSON; returns the event count."""
    from raft_tpu.observability.exporters import export_perfetto

    events = _tail_filter(report.get("events") or [], last_s)
    trace = export_perfetto(_ReplayRecorder(events))
    trace["raft_tpu"] = {
        "source": "postmortem",
        "blackbox": report.get("path"),
        "verdict": report.get("verdict"),
        "pid": report.get("pid"),
        "wall_start": report.get("wall_start"),
        "last_s": last_s,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, out_path)
    return len(events)


def _fmt_wall(wall: Optional[float]) -> str:
    if not wall:
        return "?"
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(wall))


def render_report(report: Dict, tail: int = 16) -> str:
    """The human rendering of :func:`blackbox.reconstruct`."""
    lines = []
    w = lines.append
    w(f"blackbox: {report['path']}")
    w(f"verdict:  {report['verdict'].upper()}")
    w(f"run:      pid {report['pid']}, started "
      f"{_fmt_wall(report.get('wall_start'))}")
    w(f"records:  {report['records']} recovered "
      f"({report['torn_records']} torn candidate(s), "
      f"{report['undecodable_records']} undecodable), "
      f"{len(report['events'])} flight events, "
      f"{report['snapshots']} snapshot(s)")
    epi = report.get("epilogue")
    if epi is not None:
        w(f"epilogue: reason={epi.get('reason')!r} after "
          f"{epi.get('records')} records")
    else:
        w("epilogue: MISSING — the process did not shut down cleanly")
    for stall in report.get("stall_events") or []:
        w(f"stall:    {stall.get('name')} age_s={stall.get('age_s')} "
          f"inflight={stall.get('inflight')}")
    firing = report.get("firing_alerts") or []
    if firing:
        w("alerts firing at death:")
        for a in firing:
            w(f"  {a.get('name')} severity={a.get('severity')}")
    inflight = report.get("inflight")
    if inflight:
        w(f"in-flight at death ({len(inflight)} request(s)):")
        for r in inflight[:12]:
            w(f"  rid={r.get('rid')} kind={r.get('kind')} "
              f"rows={r.get('rows')} age_s={r.get('age_s')} "
              f"deadline_in_s={r.get('deadline_in_s')}")
        if len(inflight) > 12:
            w(f"  ... {len(inflight) - 12} more")
    snap = report.get("final_snapshot")
    if snap is not None:
        metrics = snap.get("metrics") or {}
        w(f"final metrics snapshot ({_fmt_wall(snap.get('wall'))}, "
          f"{len(metrics)} series):")
        for key in sorted(metrics):
            val = metrics[key]
            if isinstance(val, dict):
                w(f"  {key}: count={val.get('count')} "
                  f"p50={val.get('p50')} p99={val.get('p99')}")
            else:
                w(f"  {key}: {val}")
    events = report.get("events") or []
    if events:
        w(f"newest flight events (last {min(tail, len(events))} "
          f"of {len(events)}):")
        for ev in events[-tail:]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("kind", "name", "ts", "ph", "lane",
                                  "stack")}
            w(f"  [{ev.get('ts', 0):.6f}] {ev.get('kind')}"
              f"/{ev.get('name')} lane={ev.get('lane')}"
              + (f" {extra}" if extra else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="blackbox ring file from a dead run")
    ap.add_argument("--json", action="store_true",
                    help="emit the full reconstruction as JSON")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write the reconstructed timeline as "
                         "Perfetto/Chrome trace JSON")
    ap.add_argument("--last-s", type=float, default=None,
                    help="restrict --trace to the final N seconds")
    ap.add_argument("--tail", type=int, default=16,
                    help="flight events to print (default 16)")
    args = ap.parse_args(argv)

    from raft_tpu.observability.blackbox import reconstruct

    report = reconstruct(args.path)
    if report is None:
        print(f"postmortem: {args.path}: not a readable blackbox file",
              file=sys.stderr)
        return 1
    if args.trace:
        n = write_trace(report, args.trace, last_s=args.last_s)
        report["trace_path"] = args.trace
        report["trace_events"] = n
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_report(report, tail=args.tail))
        if args.trace:
            print(f"trace:    {args.trace} "
                  f"({report['trace_events']} events) — open at "
                  f"https://ui.perfetto.dev")
    return 0 if report["verdict"] == "clean" else 2


if __name__ == "__main__":
    raise SystemExit(main())
